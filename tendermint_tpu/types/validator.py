"""Validator (reference: types/validator.go,
proto/tendermint/types/validator.proto)."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.crypto import keys
from tendermint_tpu.encoding import proto

# Matches types/validator_set.go:MaxTotalVotingPower = MaxInt64 / 8
MAX_TOTAL_VOTING_POWER = (2**63 - 1) // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2

_INT64_MAX = 2**63 - 1
_INT64_MIN = -(2**63)


def clip_int64(v: int) -> int:
    return max(_INT64_MIN, min(_INT64_MAX, v))


def pubkey_proto_bytes(pub: keys.PubKey) -> bytes:
    """tendermint.crypto.PublicKey oneof marshal (reference:
    crypto/encoding/codec.go PubKeyToProto; keys.proto fields: ed25519=1,
    secp256k1=2).

    EXTENSION: sr25519 = 3. The v0.34 reference ships an sr25519 key type
    but cannot proto-encode it (codec.go:35-38 errors), so sr25519
    validators can't exist in a reference validator set at all; field 3 is
    the convention forks that do support it use. Wire compatibility for
    ed25519/secp256k1 chains is unaffected."""
    field_num = {"ed25519": 1, "secp256k1": 2, "sr25519": 3}.get(pub.type)
    if field_num is None:
        raise ValueError(f"key type {pub.type} not representable in PublicKey proto")
    return proto.Writer().bytes(field_num, pub.bytes()).out()


def pubkey_from_proto_bytes(buf: bytes) -> keys.PubKey:
    f = proto.fields(buf)
    if 1 in f:
        return keys.pubkey_from_type_bytes("ed25519", f[1][-1])
    if 2 in f:
        return keys.pubkey_from_type_bytes("secp256k1", f[2][-1])
    if 3 in f:
        return keys.pubkey_from_type_bytes("sr25519", f[3][-1])
    raise ValueError("empty PublicKey proto")


@dataclass
class Validator:
    address: bytes
    pub_key: keys.PubKey
    voting_power: int
    proposer_priority: int = 0

    @staticmethod
    def new(pub_key: keys.PubKey, voting_power: int) -> "Validator":
        return Validator(
            address=pub_key.address(), pub_key=pub_key,
            voting_power=voting_power, proposer_priority=0,
        )

    def copy(self) -> "Validator":
        # direct ctor: dataclasses.replace costs ~5x more and sits on the
        # per-vote hot path (ValidatorSet.get_by_index returns copies)
        return Validator(self.address, self.pub_key, self.voting_power,
                         self.proposer_priority)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != keys.ADDRESS_SIZE:
            raise ValueError("validator address is the wrong size")

    def compare_proposer_priority(self, other: "Validator | None") -> "Validator":
        """Higher priority wins; ties broken by lower address (reference:
        types/validator.go:60-82)."""
        if other is None:
            return self
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise AssertionError("cannot compare identical validators")

    def bytes(self) -> bytes:
        """SimpleValidator proto marshal -- the validator-set hash leaf
        (reference: types/validator.go:117-131)."""
        return (
            proto.Writer()
            .message(1, pubkey_proto_bytes(self.pub_key))
            .varint(2, self.voting_power)
            .out()
        )

    # full Validator proto (validator.proto)
    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .bytes(1, self.address)
            .message(2, pubkey_proto_bytes(self.pub_key), always=True)
            .varint(3, self.voting_power)
            .varint(4, self.proposer_priority)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Validator":
        f = proto.fields(buf)
        return Validator(
            address=f.get(1, [b""])[-1],
            pub_key=pubkey_from_proto_bytes(f.get(2, [b""])[-1]),
            voting_power=proto.as_sint64(f.get(3, [0])[-1]),
            proposer_priority=proto.as_sint64(f.get(4, [0])[-1]),
        )

    def __str__(self) -> str:
        return f"Validator{{{self.address.hex()[:12]} VP:{self.voting_power} A:{self.proposer_priority}}}"
