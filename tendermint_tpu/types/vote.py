"""Vote and its canonical sign-bytes (reference: types/vote.go:50,93,147,
types/canonical.go:56, proto/tendermint/types/{types,canonical}.proto).

Sign-bytes are the varint-length-delimited marshal of CanonicalVote:
  1 type (varint)   2 height (sfixed64)   3 round (sfixed64)
  4 block_id (nullable: omitted when vote is nil)
  5 timestamp (non-nullable: always emitted)   6 chain_id
Byte-compatibility here is what lets the TPU batch verifier reproduce the
exact signatures the reference network produces.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.crypto import keys
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.ttime import Time

# SignedMsgType (proto/tendermint/types/types.proto:24-37)
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32

# BlockIDFlag (proto/tendermint/types/types.proto:13-22)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


def canonical_block_id_bytes(bid: BlockID) -> bytes | None:
    """CanonicalBlockID marshal, or None for a zero (nil-vote) BlockID
    (reference: types/canonical.go:18)."""
    if bid.is_zero():
        return None
    return (
        proto.Writer()
        .bytes(1, bid.hash)
        .message(2, bid.part_set_header.marshal(), always=True)
        .out()
    )


_CV_TEMPLATES: dict = {}


def canonical_vote_bytes(chain_id: str, vtype: int, height: int, round_: int,
                         block_id: BlockID, timestamp: Time) -> bytes:
    """Delimited CanonicalVote marshal = the exact signed payload
    (reference: types/vote.go:93 VoteSignBytes).

    In a vote drain every field except the timestamp repeats per
    (chain_id, type, height, round, block_id), so the constant prefix and
    suffix are templated (bounded cache) and the timestamp spliced in —
    differential-tested against the plain construction."""
    key = (chain_id, vtype, height, round_,
           block_id.hash, block_id.part_set_header.total,
           block_id.part_set_header.hash)
    tmpl = _CV_TEMPLATES.get(key)
    if tmpl is None:
        if len(_CV_TEMPLATES) >= 64:  # a handful of (height, round) shapes live at once
            _CV_TEMPLATES.clear()
        w = proto.Writer()
        w.varint(1, vtype)
        w.sfixed64(2, height)
        w.sfixed64(3, round_)
        cbid = canonical_block_id_bytes(block_id)
        if cbid is not None:
            w.message(4, cbid, always=True)
        tmpl = (w.out(), proto.Writer().string(6, chain_id).out())
        _CV_TEMPLATES[key] = tmpl
    pre, suf = tmpl
    tsm = timestamp.marshal()
    # field 5 (timestamp), wire type 2: tag 0x2a; always emitted.
    return proto.delimited(pre + b"\x2a" + proto.encode_uvarint(len(tsm))
                           + tsm + suf)


@dataclass
class Vote:
    type: int = UNKNOWN_TYPE
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Time = field(default_factory=Time.zero)
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_vote_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp
        )

    def verify(self, chain_id: str, pub_key: keys.PubKey) -> None:
        """Reference: types/vote.go:147 -- address match then sig verify."""
        if pub_key.address() != self.validator_address:
            raise VoteError("invalid validator address")
        if not pub_key.verify_signature(self.sign_bytes(chain_id), self.signature):
            raise VoteError("invalid signature")

    def validate_basic(self) -> None:
        if not is_vote_type_valid(self.type):
            raise VoteError("invalid Type")
        if self.height < 0:
            raise VoteError("negative Height")
        if self.round < 0:
            raise VoteError("negative Round")
        if not self.block_id.is_zero():
            self.block_id.validate_basic()
            if not self.block_id.is_complete():
                raise VoteError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != keys.ADDRESS_SIZE:
            raise VoteError("expected ValidatorAddress size to be 20 bytes")
        if self.validator_index < 0:
            raise VoteError("negative ValidatorIndex")
        if len(self.signature) == 0:
            raise VoteError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise VoteError("signature is too big")

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def copy(self) -> "Vote":
        return replace(self)

    # --- wire (proto/tendermint/types/types.proto Vote) --------------------
    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .varint(1, self.type)
            .varint(2, self.height)
            .varint(3, self.round)
            .message(4, self.block_id.marshal(), always=True)
            .message(5, self.timestamp.marshal(), always=True)
            .bytes(6, self.validator_address)
            .varint(7, self.validator_index)
            .bytes(8, self.signature)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Vote":
        f = proto.fields(buf)
        return Vote(
            type=f.get(1, [0])[-1],
            height=proto.as_sint64(f.get(2, [0])[-1]),
            round=proto.as_sint64(f.get(3, [0])[-1]),
            block_id=BlockID.unmarshal(f.get(4, [b""])[-1]),
            timestamp=Time.unmarshal(f.get(5, [b""])[-1]),
            validator_address=f.get(6, [b""])[-1],
            validator_index=proto.as_sint64(f.get(7, [0])[-1]),
            signature=f.get(8, [b""])[-1],
        )

    def __str__(self) -> str:
        kind = {PREVOTE_TYPE: "Prevote", PRECOMMIT_TYPE: "Precommit"}.get(self.type, "?")
        tgt = "nil" if self.is_nil() else self.block_id.hash.hex()[:12]
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:12]} "
            f"{self.height}/{self.round:02d} {kind} {tgt}}}"
        )


MAX_SIGNATURE_SIZE = 64  # largest among ed25519/sr25519/secp256k1 (reference: types/vote.go)


class VoteError(Exception):
    pass


class ErrVoteConflictingVotes(VoteError):
    """Same validator signed two different votes for the same H/R/T
    (reference: types/vote_set.go:84, the evidence trigger)."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__(f"conflicting votes: {vote_a} vs {vote_b}")
        self.vote_a = vote_a
        self.vote_b = vote_b


class ErrVoteNonDeterministicSignature(VoteError):
    pass
