"""Vote and its canonical sign-bytes (reference: types/vote.go:50,93,147,
types/canonical.go:56, proto/tendermint/types/{types,canonical}.proto).

Sign-bytes are the varint-length-delimited marshal of CanonicalVote:
  1 type (varint)   2 height (sfixed64)   3 round (sfixed64)
  4 block_id (nullable: omitted when vote is nil)
  5 timestamp (non-nullable: always emitted)   6 chain_id
Byte-compatibility here is what lets the TPU batch verifier reproduce the
exact signatures the reference network produces.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.crypto import keys
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.ttime import Time

# SignedMsgType (proto/tendermint/types/types.proto:24-37)
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32

# BlockIDFlag (proto/tendermint/types/types.proto:13-22)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


def canonical_block_id_bytes(bid: BlockID) -> bytes | None:
    """CanonicalBlockID marshal, or None for a zero (nil-vote) BlockID
    (reference: types/canonical.go:18)."""
    if bid.is_zero():
        return None
    return (
        proto.Writer()
        .bytes(1, bid.hash)
        .message(2, bid.part_set_header.marshal(), always=True)
        .out()
    )


_CV_TEMPLATES: dict = {}


def canonical_vote_bytes(chain_id: str, vtype: int, height: int, round_: int,
                         block_id: BlockID, timestamp: Time) -> bytes:
    """Delimited CanonicalVote marshal = the exact signed payload
    (reference: types/vote.go:93 VoteSignBytes).

    Fast path: for the ubiquitous shape (32-byte hashes, small part total,
    non-nil block) the byte layout is fixed given (chain_id, vtype, round,
    total) — height is sfixed64 — so a splice template fills in height,
    hashes and timestamp with one join instead of a Writer build per call.
    The template is SELF-CHECKED against the Writer construction when
    built: layout drift disables the fast path for that key rather than
    ever signing wrong bytes. Light-client range sync builds one of these
    per header; a cache keyed on (height, block_id) missed every time
    there."""
    psh = block_id.part_set_header
    if not (len(block_id.hash) == 32 and len(psh.hash) == 32
            and 0 < psh.total < 128 and 0 < height < 2**63
            and 0 <= round_ < 2**63 and vtype != 0):
        # height 0 is never signed; zero-valued proto fields are omitted by
        # the Writer, so the fixed-layout assumption needs height > 0
        return _canonical_vote_bytes_writer(
            chain_id, vtype, height, round_, block_id, timestamp)
    key = (chain_id, vtype, round_, psh.total)
    tmpl = _CV_TEMPLATES.get(key, False)
    if tmpl is False:
        if len(_CV_TEMPLATES) >= 64:
            _CV_TEMPLATES.clear()
        # layout: head|height8|mid1|bid.hash|mid2|psh.hash|ts|suffix
        psh_inner = 1 + len(proto.encode_uvarint(psh.total)) + 2 + 32
        f4_inner = 2 + 32 + 1 + len(proto.encode_uvarint(psh_inner)) + psh_inner
        head = proto.Writer().varint(1, vtype).out() + b"\x11"
        # round 0 (the common prevote/precommit round) is omitted entirely,
        # like every zero-valued proto field the Writer drops
        round_seg = (b"" if round_ == 0
                     else b"\x19" + round_.to_bytes(8, "little"))
        mid1 = (round_seg
                + b"\x22" + proto.encode_uvarint(f4_inner) + b"\x0a\x20")
        mid2 = (b"\x12" + proto.encode_uvarint(psh_inner)
                + b"\x08" + proto.encode_uvarint(psh.total) + b"\x12\x20")
        suf = proto.Writer().string(6, chain_id).out()
        tmpl = (head, mid1, mid2, suf)
        # self-check: any drift between this splice layout and the Writer
        # path falls back to the Writer permanently for this key
        chk_bid = BlockID(hash=b"\xa7" * 32,
                          part_set_header=PartSetHeader(psh.total, b"\x5c" * 32))
        chk_ts = Time(123456789, 987)
        tsm = chk_ts.marshal()
        fast = proto.delimited(
            head + (54321).to_bytes(8, "little") + mid1 + chk_bid.hash
            + mid2 + chk_bid.part_set_header.hash
            + b"\x2a" + proto.encode_uvarint(len(tsm)) + tsm + suf)
        if fast != _canonical_vote_bytes_writer(
                chain_id, vtype, 54321, round_, chk_bid, chk_ts):
            tmpl = None
        _CV_TEMPLATES[key] = tmpl
    if tmpl is None:
        return _canonical_vote_bytes_writer(
            chain_id, vtype, height, round_, block_id, timestamp)
    head, mid1, mid2, suf = tmpl
    tsm = timestamp.marshal()
    return proto.delimited(
        head + height.to_bytes(8, "little") + mid1 + block_id.hash
        + mid2 + psh.hash + b"\x2a" + proto.encode_uvarint(len(tsm)) + tsm + suf)


def _canonical_vote_bytes_writer(chain_id: str, vtype: int, height: int,
                                 round_: int, block_id: BlockID,
                                 timestamp: Time) -> bytes:
    """Plain Writer-based construction (the layout source of truth)."""
    w = proto.Writer()
    w.varint(1, vtype)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        w.message(4, cbid, always=True)
    pre = w.out()
    suf = proto.Writer().string(6, chain_id).out()
    tsm = timestamp.marshal()
    # field 5 (timestamp), wire type 2: tag 0x2a; always emitted.
    return proto.delimited(pre + b"\x2a" + proto.encode_uvarint(len(tsm))
                           + tsm + suf)


@dataclass
class Vote:
    type: int = UNKNOWN_TYPE
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Time = field(default_factory=Time.zero)
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_vote_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp
        )

    def verify(self, chain_id: str, pub_key: keys.PubKey) -> None:
        """Reference: types/vote.go:147 -- address match then sig verify."""
        if pub_key.address() != self.validator_address:
            raise VoteError("invalid validator address")
        if not pub_key.verify_signature(self.sign_bytes(chain_id), self.signature):
            raise VoteError("invalid signature")

    def validate_basic(self) -> None:
        if not is_vote_type_valid(self.type):
            raise VoteError("invalid Type")
        if self.height < 0:
            raise VoteError("negative Height")
        if self.round < 0:
            raise VoteError("negative Round")
        if not self.block_id.is_zero():
            self.block_id.validate_basic()
            if not self.block_id.is_complete():
                raise VoteError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != keys.ADDRESS_SIZE:
            raise VoteError("expected ValidatorAddress size to be 20 bytes")
        if self.validator_index < 0:
            raise VoteError("negative ValidatorIndex")
        if len(self.signature) == 0:
            raise VoteError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise VoteError("signature is too big")

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def copy(self) -> "Vote":
        return replace(self)

    # --- wire (proto/tendermint/types/types.proto Vote) --------------------
    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .varint(1, self.type)
            .varint(2, self.height)
            .varint(3, self.round)
            .message(4, self.block_id.marshal(), always=True)
            .message(5, self.timestamp.marshal(), always=True)
            .bytes(6, self.validator_address)
            .varint(7, self.validator_index)
            .bytes(8, self.signature)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Vote":
        f = proto.fields(buf)
        return Vote(
            type=f.get(1, [0])[-1],
            height=proto.as_sint64(f.get(2, [0])[-1]),
            round=proto.as_sint64(f.get(3, [0])[-1]),
            block_id=BlockID.unmarshal(f.get(4, [b""])[-1]),
            timestamp=Time.unmarshal(f.get(5, [b""])[-1]),
            validator_address=f.get(6, [b""])[-1],
            validator_index=proto.as_sint64(f.get(7, [0])[-1]),
            signature=f.get(8, [b""])[-1],
        )

    def __str__(self) -> str:
        kind = {PREVOTE_TYPE: "Prevote", PRECOMMIT_TYPE: "Precommit"}.get(self.type, "?")
        tgt = "nil" if self.is_nil() else self.block_id.hash.hex()[:12]
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:12]} "
            f"{self.height}/{self.round:02d} {kind} {tgt}}}"
        )


MAX_SIGNATURE_SIZE = 64  # largest among ed25519/sr25519/secp256k1 (reference: types/vote.go)


class VoteError(Exception):
    pass


class ErrVoteConflictingVotes(VoteError):
    """Same validator signed two different votes for the same H/R/T
    (reference: types/vote_set.go:84, the evidence trigger)."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__(f"conflicting votes: {vote_a} vs {vote_b}")
        self.vote_a = vote_a
        self.vote_b = vote_b


class ErrVoteNonDeterministicSignature(VoteError):
    pass


class ErrVoteInvalidSignature(VoteError):
    """Signature verification failed — the one vote error whose blame is
    unambiguous: votes are gossip-relayed, but a relay corrupting a vote
    is as culpable as a forger, so the peer misbehavior scoreboard
    (utils/peerscore.py) scores the delivering peer on this type."""
