"""GenesisDoc (reference: types/genesis.go)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from tendermint_tpu.crypto import keys, tmhash
from tendermint_tpu.types.params import (
    BlockParams,
    ConsensusParams,
    EvidenceParams,
    ValidatorParams,
    VersionParams,
)
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator

MAX_CHAIN_ID_LEN = 50


@dataclass
class GenesisValidator:
    address: bytes
    pub_key: keys.PubKey
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    genesis_time: Time = field(default_factory=Time.zero)
    chain_id: str = ""
    initial_height: int = 1
    consensus_params: ConsensusParams | None = None
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validate_and_complete(self) -> None:
        """reference: types/genesis.go:60-103."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = ConsensusParams()
        else:
            self.consensus_params.validate_basic()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"the genesis file cannot contain validators with no voting power: {v}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i} in the genesis file")
            if not v.address:
                v.address = v.pub_key.address()
        if self.genesis_time.is_zero():
            self.genesis_time = Time.now()

    def validator_hash(self) -> bytes:
        from tendermint_tpu.crypto import merkle

        vals = [Validator.new(v.pub_key, v.power) for v in self.validators]
        return merkle.hash_from_byte_slices([v.bytes() for v in vals])

    # --- JSON round trip (operator-facing file format) ---------------------

    def to_json(self) -> str:
        def enc_val(v: GenesisValidator):
            return {
                "address": v.address.hex().upper(),
                "pub_key": {
                    "type": _pubkey_json_type(v.pub_key.type),
                    "value": _b64(v.pub_key.bytes()),
                },
                "power": str(v.power),
                "name": v.name,
            }

        cp = self.consensus_params or ConsensusParams()
        doc = {
            "genesis_time": str(self.genesis_time),
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": {
                "block": {
                    "max_bytes": str(cp.block.max_bytes),
                    "max_gas": str(cp.block.max_gas),
                    "time_iota_ms": str(cp.block.time_iota_ms),
                },
                "evidence": {
                    "max_age_num_blocks": str(cp.evidence.max_age_num_blocks),
                    "max_age_duration": str(cp.evidence.max_age_duration_ns),
                    "max_bytes": str(cp.evidence.max_bytes),
                },
                "validator": {"pub_key_types": list(cp.validator.pub_key_types)},
                "version": {"app_version": str(cp.version.app_version)},
            },
            "validators": [enc_val(v) for v in self.validators],
            "app_hash": self.app_hash.hex().upper(),
            "app_state": json.loads(self.app_state.decode() or "{}"),
        }
        return json.dumps(doc, indent=2)

    @staticmethod
    def from_json(data: str) -> "GenesisDoc":
        doc = json.loads(data)
        vals = []
        for v in doc.get("validators") or []:
            pk = keys.pubkey_from_type_bytes(
                _pubkey_type_from_json(v["pub_key"]["type"]), _unb64(v["pub_key"]["value"])
            )
            vals.append(
                GenesisValidator(
                    address=bytes.fromhex(v.get("address", "")),
                    pub_key=pk,
                    power=int(v["power"]),
                    name=v.get("name", ""),
                )
            )
        cp_doc = doc.get("consensus_params")
        cp = None
        if cp_doc:
            cp = ConsensusParams(
                block=BlockParams(
                    max_bytes=int(cp_doc["block"]["max_bytes"]),
                    max_gas=int(cp_doc["block"]["max_gas"]),
                    time_iota_ms=int(cp_doc["block"].get("time_iota_ms", 1000)),
                ),
                evidence=EvidenceParams(
                    max_age_num_blocks=int(cp_doc["evidence"]["max_age_num_blocks"]),
                    max_age_duration_ns=int(cp_doc["evidence"]["max_age_duration"]),
                    max_bytes=int(cp_doc["evidence"].get("max_bytes", 1048576)),
                ),
                validator=ValidatorParams(
                    pub_key_types=tuple(cp_doc["validator"]["pub_key_types"])
                ),
                version=VersionParams(
                    app_version=int(cp_doc.get("version", {}).get("app_version", 0))
                ),
            )
        gd = GenesisDoc(
            genesis_time=_parse_time(doc.get("genesis_time", "")),
            chain_id=doc["chain_id"],
            initial_height=int(doc.get("initial_height", 1)),
            consensus_params=cp,
            validators=vals,
            app_hash=bytes.fromhex(doc.get("app_hash", "")),
            app_state=json.dumps(doc.get("app_state", {})).encode(),
        )
        gd.validate_and_complete()
        return gd

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def from_file(path: str) -> "GenesisDoc":
        with open(path) as f:
            return GenesisDoc.from_json(f.read())


def _b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    import base64

    return base64.b64decode(s)


def _pubkey_json_type(t: str) -> str:
    return {
        "ed25519": "tendermint/PubKeyEd25519",
        "secp256k1": "tendermint/PubKeySecp256k1",
        "sr25519": "tendermint/PubKeySr25519",
    }[t]


def _pubkey_type_from_json(t: str) -> str:
    return {
        "tendermint/PubKeyEd25519": "ed25519",
        "tendermint/PubKeySecp256k1": "secp256k1",
        "tendermint/PubKeySr25519": "sr25519",
    }[t]


def _parse_time(s: str) -> Time:
    if not s or s.startswith("0001-01-01"):
        return Time.zero()
    import calendar
    import re

    m = re.match(r"(\d+)-(\d+)-(\d+)T(\d+):(\d+):(\d+)(\.\d+)?Z?", s)
    if not m:
        return Time.zero()
    secs = calendar.timegm(
        (int(m[1]), int(m[2]), int(m[3]), int(m[4]), int(m[5]), int(m[6]), 0, 0, 0)
    )
    nanos = int(float(m[7] or 0) * 1e9)
    return Time(secs, nanos)
