"""Evidence types (reference: types/evidence.go:22-320,
proto/tendermint/types/evidence.proto).

DuplicateVoteEvidence: a validator signed two conflicting votes at the same
H/R/T. LightClientAttackEvidence: a conflicting light block with common
ancestor, listing byzantine validators.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import Vote


class EvidenceError(Exception):
    """Typed evidence rejection. ``reason`` is a closed label set consumed
    by the evidence reactor's rejection counter and peer scoring
    (evidence/reactor.py, ``evidence_rejected_total{reason}``):
    expired / bad_sig / unknown_validator / meta_mismatch / malformed /
    invalid."""

    REASONS = ("expired", "bad_sig", "unknown_validator", "meta_mismatch",
               "malformed", "invalid")

    def __init__(self, msg: str = "", reason: str = "invalid"):
        super().__init__(msg)
        self.reason = reason if reason in self.REASONS else "invalid"


@dataclass
class DuplicateVoteEvidence:
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: Time = field(default_factory=Time.zero)

    @staticmethod
    def new(vote1: Vote, vote2: Vote, block_time: Time, val_set) -> "DuplicateVoteEvidence | None":
        """Orders votes by BlockID key (reference: types/evidence.go:49-74)."""
        if vote1 is None or vote2 is None or val_set is None:
            return None
        idx, val = val_set.get_by_address(vote1.validator_address)
        if idx == -1:
            return None
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return DuplicateVoteEvidence(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def _inner(self) -> bytes:
        return (
            proto.Writer()
            .message(1, self.vote_a.marshal())
            .message(2, self.vote_b.marshal())
            .varint(3, self.total_voting_power)
            .varint(4, self.validator_power)
            .message(5, self.timestamp.marshal(), always=True)
            .out()
        )

    def bytes(self) -> bytes:
        """Evidence-oneof wrapper marshal (reference: types/evidence.go:90)."""
        return proto.Writer().message(1, self._inner(), always=True).out()

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    def height(self) -> int:
        return self.vote_a.height

    def time(self) -> Time:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise EvidenceError("empty duplicate vote evidence")
        if not self.vote_a.signature or not self.vote_b.signature:
            raise EvidenceError("empty signature")
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise EvidenceError("duplicate votes in invalid order (or the same block id)")

    def __str__(self) -> str:
        return (
            f"DuplicateVoteEvidence{{VoteA: {self.vote_a}, VoteB: {self.vote_b}}}"
        )

    @staticmethod
    def unmarshal_inner(buf: bytes) -> "DuplicateVoteEvidence":
        f = proto.fields(buf)
        return DuplicateVoteEvidence(
            vote_a=Vote.unmarshal(f.get(1, [b""])[-1]),
            vote_b=Vote.unmarshal(f.get(2, [b""])[-1]),
            total_voting_power=proto.as_sint64(f.get(3, [0])[-1]),
            validator_power=proto.as_sint64(f.get(4, [0])[-1]),
            timestamp=Time.unmarshal(f.get(5, [b""])[-1]),
        )


@dataclass
class LightClientAttackEvidence:
    conflicting_block: object  # light.LightBlock (SignedHeader + ValidatorSet)
    common_height: int
    byzantine_validators: list = field(default_factory=list)
    total_voting_power: int = 0
    timestamp: Time = field(default_factory=Time.zero)

    def _inner(self) -> bytes:
        w = proto.Writer()
        if self.conflicting_block is not None:
            w.message(1, self.conflicting_block.marshal())
        w.varint(2, self.common_height)
        for v in self.byzantine_validators:
            w.message(3, v.marshal())
        w.varint(4, self.total_voting_power)
        w.message(5, self.timestamp.marshal(), always=True)
        return w.out()

    def bytes(self) -> bytes:
        return proto.Writer().message(2, self._inner(), always=True).out()

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    def height(self) -> int:
        return self.common_height

    def time(self) -> Time:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise EvidenceError("conflicting block is nil")
        if self.common_height <= 0:
            raise EvidenceError("negative or zero common height")

    def conflicting_header_is_invalid(self, trusted_header) -> bool:
        """Lunatic test: a correctly-derived conflicting header would share
        every state-derived field with our trusted header at that height
        (reference: types/evidence.go:219 ConflictingHeaderIsInvalid)."""
        ch = self.conflicting_block.signed_header.header
        return (trusted_header.validators_hash != ch.validators_hash
                or trusted_header.next_validators_hash != ch.next_validators_hash
                or trusted_header.consensus_hash != ch.consensus_hash
                or trusted_header.app_hash != ch.app_hash
                or trusted_header.last_results_hash != ch.last_results_hash)

    def get_byzantine_validators(self, common_vals, trusted_sh) -> list:
        """The validators provably at fault for this attack (reference:
        types/evidence.go:233 GetByzantineValidators).

        Lunatic (invalid header): members of the COMMON set that signed the
        fabricated block. Equivocation (same round): validators that signed
        both commits. Amnesia (different round, derived header): not
        attributable from the two commits alone -> empty."""
        ch = self.conflicting_block.signed_header
        out = []
        if self.conflicting_header_is_invalid(trusted_sh.header):
            for sig in ch.commit.signatures:
                if not sig.for_block():
                    continue
                _, val = common_vals.get_by_address(sig.validator_address)
                if val is not None:
                    out.append(val)
        elif trusted_sh.commit.round == ch.commit.round:
            for sig_a, sig_b in zip(ch.commit.signatures,
                                    trusted_sh.commit.signatures):
                if sig_a.absent() or sig_b.absent():
                    continue
                _, val = self.conflicting_block.validator_set.get_by_address(
                    sig_a.validator_address)
                if val is not None:
                    out.append(val)
        else:
            return []
        out.sort(key=lambda v: (-v.voting_power, v.address))
        return out

    def __str__(self) -> str:
        return (
            f"LightClientAttackEvidence{{CommonHeight: {self.common_height}, "
            f"Byzantine: {len(self.byzantine_validators)}}}"
        )

    @staticmethod
    def unmarshal_inner(buf: bytes) -> "LightClientAttackEvidence":
        from tendermint_tpu.types.validator import Validator

        f = proto.fields(buf)
        cb = None
        if 1 in f:
            from tendermint_tpu.types.light_block import LightBlock

            cb = LightBlock.unmarshal(f[1][-1])
        return LightClientAttackEvidence(
            conflicting_block=cb,
            common_height=proto.as_sint64(f.get(2, [0])[-1]),
            byzantine_validators=[Validator.unmarshal(b) for b in f.get(3, [])],
            total_voting_power=proto.as_sint64(f.get(4, [0])[-1]),
            timestamp=Time.unmarshal(f.get(5, [b""])[-1]),
        )


def evidence_unmarshal(buf: bytes):
    """Evidence oneof decode."""
    f = proto.fields(buf)
    if 1 in f:
        return DuplicateVoteEvidence.unmarshal_inner(f[1][-1])
    if 2 in f:
        return LightClientAttackEvidence.unmarshal_inner(f[2][-1])
    raise EvidenceError("unknown evidence type", reason="malformed")
