"""ValidatorSet: ordering, proposer rotation, and the batched commit
verification paths (reference: types/validator_set.go:70,107-180,660-830).

The three Verify* entry points are where the reference burns one serial
ed25519 verify per validator (~70-100us each). Here every signature needed by
the serial decision procedure is queued into one BatchVerifier flush (one TPU
kernel launch), and the reference's *exact* accept/reject + error-attribution
semantics are then replayed over the returned bitmap:

 - VerifyCommit checks ALL signatures (incentivization, see reference comment
   types/validator_set.go:662-666) and fails on the first invalid index;
 - VerifyCommitLight / VerifyCommitLightTrusting stop tallying at +2/3 - in
   the serial code later signatures are NEVER verified, so an invalid
   signature after the threshold does not fail the call. We reproduce that by
   ignoring bitmap entries past the serial stopping point.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto import merkle
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator import (
    MAX_TOTAL_VOTING_POWER,
    PRIORITY_WINDOW_SIZE_FACTOR,
    Validator,
    clip_int64,
)

# Implied validator-set size cap (reference: types/validator_set.go MaxVotesCount)
MAX_VOTES_COUNT = 10000


class ValidatorSetError(Exception):
    pass


class ErrNotEnoughVotingPowerSigned(ValidatorSetError):
    def __init__(self, got: int, needed: int):
        super().__init__(f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}")
        self.got = got
        self.needed = needed


class ErrInvalidCommitSignatures(ValidatorSetError):
    def __init__(self, have: int, want: int):
        super().__init__(f"invalid commit -- wrong set size: {have} vs {want}")


class ErrInvalidCommitHeight(ValidatorSetError):
    def __init__(self, want: int, got: int):
        super().__init__(f"invalid commit -- wrong height: {want} vs {got}")


class ErrWrongSignature(ValidatorSetError):
    def __init__(self, idx: int, sig: bytes):
        super().__init__(f"wrong signature (#{idx}): {sig.hex().upper()}")
        self.index = idx


class PendingCommitVerify:
    """A dispatched-but-undecided commit verification (the cross-decision
    pipeline handle of verify_commit_async / verify_commit_light_async).

    All host prep and device dispatch happened at creation; ``resolve()``
    performs the (possibly batched-away) readback and replays the EXACT
    serial accept/reject decision procedure, raising precisely what the
    synchronous call would have raised — structural errors captured at
    dispatch time included, so error ordering per decision is unchanged.
    Decision inputs (stopping prefix, voting powers, threshold) are frozen
    at dispatch: a caller that mutates the ValidatorSet afterwards gets the
    dispatch-time decision, the only sane semantics for speculative
    verification (the fast-sync pipeline discards handles whose validator
    set changed before their turn).

    ``pending`` exposes the underlying crypto-layer
    :class:`~tendermint_tpu.crypto.batch.PendingVerify` (None when the
    decision needed no device work) so callers with several decisions in
    flight can batch the readbacks into one device_get
    (crypto_batch.prefetch)."""

    __slots__ = ("pending", "_finalize", "_error")

    def __init__(self, pending=None, finalize=None, error: Exception | None = None):
        self.pending = pending
        self._finalize = finalize
        self._error = error

    def resolve(self) -> None:
        """Raises exactly what the synchronous verify would; returns None on
        accept. Idempotent: the bitmap is cached by the crypto layer and the
        decision replay is deterministic."""
        if self._error is not None:
            raise self._error
        bitmap: list[bool] = []
        if self.pending is not None:
            _, bitmap = self.pending.resolve()
        self._finalize(bitmap)


class ValidatorSet:
    """Sorted by voting power desc, then address asc. Not thread-safe."""

    def __init__(self, validators: list[Validator] | None = None):
        self.validators: list[Validator] = []
        self.proposer: Validator | None = None
        self._total_voting_power = 0
        if validators is not None:
            self._update_with_change_set(
                [v.copy() for v in validators], allow_deletes=False
            )
            if validators:
                self.increment_proposer_priority(1)

    # --- basic accessors ---------------------------------------------------

    def size(self) -> int:
        return len(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    def is_nil_or_empty(self) -> bool:
        return len(self.validators) == 0

    def has_address(self, address: bytes) -> bool:
        return any(v.address == address for v in self.validators)

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v.copy()
        return -1, None

    def get_by_index(self, index: int) -> tuple[bytes | None, Validator | None]:
        if index < 0 or index >= len(self.validators):
            return None, None
        v = self.validators[index]
        return v.address, v.copy()

    def total_voting_power(self) -> int:
        if self._total_voting_power == 0:
            self._update_total_voting_power()
        return self._total_voting_power

    def _update_total_voting_power(self) -> None:
        s = 0
        for v in self.validators:
            s = clip_int64(s + v.voting_power)
            if s > MAX_TOTAL_VOTING_POWER:
                raise ValidatorSetError(
                    f"total voting power exceeds max {MAX_TOTAL_VOTING_POWER}: {s}"
                )
        self._total_voting_power = s

    def copy(self) -> "ValidatorSet":
        new = ValidatorSet()
        new.validators = [v.copy() for v in self.validators]
        new.proposer = self.proposer
        new._total_voting_power = self._total_voting_power
        # the set hash covers (pubkey, power) only, both copied verbatim
        new._hash_cache = getattr(self, "_hash_cache", None)
        return new

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValidatorSetError("validator set is nil or empty")
        for i, v in enumerate(self.validators):
            try:
                v.validate_basic()
            except ValueError as e:
                raise ValidatorSetError(f"invalid validator #{i}: {e}") from e
        if self.proposer is None:
            raise ValidatorSetError("proposer failed validate basic: nil")
        self.proposer.validate_basic()

    def hash(self) -> bytes:
        """Merkle root over SimpleValidator marshals (reference:
        types/validator_set.go:346-353). Memoized: light-client range sync
        hashes the same set once per header otherwise. The cache survives
        copy() and is invalidated by update_with_change_set; proposer-
        priority rotation does not enter the hash. Direct mutation of a
        validator's power/key bypasses invalidation (same caller convention
        as Header hash caching)."""
        h = getattr(self, "_hash_cache", None)
        if h is None:
            h = merkle.hash_from_byte_slices([v.bytes() for v in self.validators])
            self._hash_cache = h
        return h

    # --- proposer rotation (reference: types/validator_set.go:107-245) -----

    def get_proposer(self) -> Validator | None:
        if not self.validators:
            return None
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        proposer = None
        for v in self.validators:
            if proposer is None or v.address != proposer.address:
                proposer = v.compare_proposer_priority(proposer)
        return proposer

    def increment_proposer_priority(self, times: int) -> None:
        if self.is_nil_or_empty():
            raise ValidatorSetError("empty validator set")
        if times <= 0:
            raise ValidatorSetError("cannot call with non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0:
            return
        diff = self._max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                # Go integer division truncates toward zero.
                q = abs(v.proposer_priority) // ratio
                v.proposer_priority = q if v.proposer_priority >= 0 else -q

    def _max_min_priority_diff(self) -> int:
        prios = [v.proposer_priority for v in self.validators]
        return abs(max(prios) - min(prios))

    def _shift_by_avg_proposer_priority(self) -> None:
        n = len(self.validators)
        # Floor-divide like Go big.Int Div (Euclidean for positive divisor).
        total = sum(v.proposer_priority for v in self.validators)
        avg = total // n if total >= 0 else -((-total + n - 1) // n)
        for v in self.validators:
            v.proposer_priority = clip_int64(v.proposer_priority - avg)

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = clip_int64(v.proposer_priority + v.voting_power)
        mostest = None
        for v in self.validators:
            mostest = v.compare_proposer_priority(mostest)
        mostest.proposer_priority = clip_int64(
            mostest.proposer_priority - self.total_voting_power()
        )
        return mostest

    # --- updates (reference: types/validator_set.go:398-650) ---------------

    def update_with_change_set(self, changes: list[Validator]) -> None:
        self._update_with_change_set([c.copy() for c in changes], allow_deletes=True)

    def _update_with_change_set(self, changes: list[Validator], allow_deletes: bool) -> None:
        if not changes:
            return
        self._hash_cache = None  # membership/power may change
        changes_sorted = sorted(changes, key=lambda v: v.address)
        for a, b in zip(changes_sorted, changes_sorted[1:]):
            if a.address == b.address:
                raise ValidatorSetError(f"duplicate entry {b} in changes")
        updates, removals = [], []
        for c in changes_sorted:
            if c.voting_power < 0:
                raise ValidatorSetError("voting power can't be negative")
            if c.voting_power > MAX_TOTAL_VOTING_POWER:
                raise ValidatorSetError(
                    f"to prevent clipping/overflow, voting power can't be higher than {MAX_TOTAL_VOTING_POWER}"
                )
            if c.voting_power == 0:
                removals.append(c)
            else:
                updates.append(c)
        if removals and not allow_deletes:
            raise ValidatorSetError(f"cannot process validators with voting power 0: {removals}")
        for r in removals:
            if not self.has_address(r.address):
                raise ValidatorSetError(
                    f"failed to find validator {r.address.hex()} to remove"
                )

        # verifyUpdates: check the updated total doesn't overflow.
        delta = 0
        by_addr = {v.address: v for v in self.validators}
        for u in updates:
            prev = by_addr.get(u.address)
            delta += u.voting_power - (prev.voting_power if prev else 0)
        removed_power = sum(
            by_addr[r.address].voting_power for r in removals if r.address in by_addr
        )
        new_total = self.total_voting_power() + delta - removed_power if self.validators else sum(
            u.voting_power for u in updates
        )
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValidatorSetError(
                f"total voting power of resulting valset exceeds max {MAX_TOTAL_VOTING_POWER}"
            )

        # computeNewPriorities: new validators start at -1.125 * new total.
        for u in updates:
            prev = by_addr.get(u.address)
            if prev is None:
                u.proposer_priority = -(new_total + (new_total >> 3))
            else:
                u.proposer_priority = prev.proposer_priority

        # apply: merge + delete, re-sort by (power desc, address asc).
        removal_addrs = {r.address for r in removals}
        merged = {v.address: v for v in self.validators}
        for u in updates:
            merged[u.address] = u
        for addr in removal_addrs:
            merged.pop(addr, None)
        self.validators = sorted(
            merged.values(), key=lambda v: (-v.voting_power, v.address)
        )
        self._total_voting_power = 0
        self._update_total_voting_power()
        if updates or removals:
            # Only rescale/recenter when something changed (updateWithChangeSet
            # tail, reference types/validator_set.go:628-644).
            self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
            self._shift_by_avg_proposer_priority()

    # --- commit verification (the TPU hot path) ----------------------------

    def _commit_structural_error(self, block_id: BlockID, height: int,
                                 commit) -> ValidatorSetError | None:
        """The shared pre-signature checks of every Verify* entry point."""
        if self.size() != len(commit.signatures):
            return ErrInvalidCommitSignatures(self.size(), len(commit.signatures))
        if height != commit.height:
            return ErrInvalidCommitHeight(height, commit.height)
        if block_id != commit.block_id:
            return ValidatorSetError(
                f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
            )
        return None

    def verify_commit(self, chain_id: str, block_id: BlockID, height: int, commit) -> None:
        """Checks ALL signatures; first bad index wins (reference:
        types/validator_set.go:660-715)."""
        self.verify_commit_async(chain_id, block_id, height, commit).resolve()

    def verify_commit_async(self, chain_id: str, block_id: BlockID, height: int,
                            commit, force_device: bool = False) -> PendingCommitVerify:
        """Deferred verify_commit: host prep + device dispatch now, the
        serial decision replay (identical errors) on resolve()."""
        err = self._commit_structural_error(block_id, height, commit)
        if err is not None:
            return PendingCommitVerify(error=err)
        verifier = crypto_batch.create_batch_verifier()
        queued: list[int] = []
        for idx, cs in enumerate(commit.signatures):
            if cs.absent():
                continue
            verifier.add(
                self.validators[idx].pub_key,
                commit.vote_sign_bytes(chain_id, idx),
                cs.signature,
            )
            queued.append(idx)
        pending = verifier.dispatch(force_device=force_device)
        # Freeze the decision inputs at dispatch time.
        needed = self.total_voting_power() * 2 // 3
        powers = [self.validators[idx].voting_power for idx in queued]
        signatures = list(commit.signatures)

        def finalize(bitmap: list[bool]) -> None:
            ok_by_idx = dict(zip(queued, bitmap))
            tallied = 0
            for idx, power in zip(queued, powers):
                cs = signatures[idx]
                if not ok_by_idx[idx]:
                    raise ErrWrongSignature(idx, cs.signature)
                if cs.for_block():
                    tallied += power
            if tallied <= needed:
                raise ErrNotEnoughVotingPowerSigned(tallied, needed)

        return PendingCommitVerify(pending, finalize)

    def commit_light_prefix(self, commit, needed: int) -> list[int]:
        """Indexes the serial VerifyCommitLight would actually verify: the
        shortest for_block prefix whose power exceeds `needed` (the reference
        stopping rule, types/validator_set.go:740-762). Shared by
        verify_commit_light and light.range_verify so the serial-semantics
        replay can never drift between them."""
        prefix: list[int] = []
        tallied = 0
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            prefix.append(idx)
            tallied += self.validators[idx].voting_power
            if tallied > needed:
                break
        return prefix

    def verify_commit_light(self, chain_id: str, block_id: BlockID, height: int, commit) -> None:
        """Stops at +2/3 like the serial code: signatures past the serial
        stopping point are not consulted (reference:
        types/validator_set.go:719-766)."""
        self.verify_commit_light_async(chain_id, block_id, height, commit).resolve()

    def verify_commit_light_async(self, chain_id: str, block_id: BlockID,
                                  height: int, commit,
                                  force_device: bool = False) -> PendingCommitVerify:
        """Deferred verify_commit_light: the fast-sync verify-ahead pipeline
        (blockchain/pipeline.py) dispatches several heights' commits through
        this, overlapping the device round trips with block save/apply, and
        replays each height's serial decision in order on resolve()."""
        err = self._commit_structural_error(block_id, height, commit)
        if err is not None:
            return PendingCommitVerify(error=err)
        needed = self.total_voting_power() * 2 // 3
        prefix = self.commit_light_prefix(commit, needed)
        verifier = crypto_batch.create_batch_verifier()
        for idx in prefix:
            verifier.add(
                self.validators[idx].pub_key,
                commit.vote_sign_bytes(chain_id, idx),
                commit.signatures[idx].signature,
            )
        pending = verifier.dispatch(force_device=force_device)
        powers = [self.validators[idx].voting_power for idx in prefix]
        signatures = list(commit.signatures)

        def finalize(bitmap: list[bool]) -> None:
            tallied = 0
            for idx, power, ok in zip(prefix, powers, bitmap):
                if not ok:
                    raise ErrWrongSignature(idx, signatures[idx].signature)
                tallied += power
                if tallied > needed:
                    return
            raise ErrNotEnoughVotingPowerSigned(tallied, needed)

        return PendingCommitVerify(pending, finalize)

    def verify_commit_light_trusting(self, chain_id: str, commit, trust_level) -> None:
        """trust_level of THIS set must have signed (reference:
        types/validator_set.go:772-830). trust_level: (numerator, denominator)."""
        num, den = trust_level
        if den == 0:
            raise ValidatorSetError("trustLevel has zero Denominator")
        total_mul = self.total_voting_power() * num
        if total_mul > 2**63 - 1:
            raise ValidatorSetError("int64 overflow while calculating voting power needed")
        needed = total_mul // den

        seen: dict[int, int] = {}
        prefix: list[tuple[int, int]] = []  # (commit idx, val idx)
        tallied_scan = 0
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val_idx, val = self.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen:
                raise ValidatorSetError(
                    f"double vote from {val} ({seen[val_idx]} and {idx})"
                )
            seen[val_idx] = idx
            prefix.append((idx, val_idx))
            tallied_scan += val.voting_power
            if tallied_scan > needed:
                break

        verifier = crypto_batch.create_batch_verifier()
        for idx, val_idx in prefix:
            verifier.add(
                self.validators[val_idx].pub_key,
                commit.vote_sign_bytes(chain_id, idx),
                commit.signatures[idx].signature,
            )
        _, bitmap = verifier.verify()

        tallied = 0
        for (idx, val_idx), ok in zip(prefix, bitmap):
            if not ok:
                raise ErrWrongSignature(idx, commit.signatures[idx].signature)
            tallied += self.validators[val_idx].voting_power
            if tallied > needed:
                return
        raise ErrNotEnoughVotingPowerSigned(tallied, needed)

    # --- wire --------------------------------------------------------------

    def marshal(self) -> bytes:
        w = proto.Writer()
        for v in self.validators:
            w.message(1, v.marshal())
        if self.proposer is not None:
            w.message(2, self.proposer.marshal())
        w.varint(3, self.total_voting_power())
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "ValidatorSet":
        f = proto.fields(buf)
        vs = ValidatorSet()
        vs.validators = [Validator.unmarshal(b) for b in f.get(1, [])]
        if 2 in f:
            vs.proposer = Validator.unmarshal(f[2][-1])
        vs._total_voting_power = 0
        return vs

    def __str__(self) -> str:
        prop = self.proposer.address.hex()[:12] if self.proposer else "nil"
        return f"ValidatorSet{{n={len(self.validators)} proposer={prop}}}"
