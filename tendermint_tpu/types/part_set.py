"""PartSet: block serialization into 64kB parts with Merkle proofs
(reference: types/part_set.go:150, types/params.go:17 BlockPartSizeBytes)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.crypto import merkle
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block_id import PartSetHeader

BLOCK_PART_SIZE_BYTES = 65536
MAX_BLOCK_SIZE_BYTES = 104857600
MAX_BLOCK_PARTS_COUNT = MAX_BLOCK_SIZE_BYTES // BLOCK_PART_SIZE_BYTES + 1


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            raise ValueError("part bytes too big")
        if self.proof.leaf_hash != merkle.leaf_hash(self.bytes_):
            raise ValueError("wrong proof leaf hash")

    def marshal(self) -> bytes:
        pw = (
            proto.Writer()
            .varint(1, self.proof.total)
            .varint(2, self.proof.index)
            .bytes(3, self.proof.leaf_hash)
        )
        for a in self.proof.aunts:
            pw.bytes(4, a)
        return (
            proto.Writer()
            .uvarint(1, self.index)
            .bytes(2, self.bytes_)
            .message(3, pw.out(), always=True)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Part":
        f = proto.fields(buf)
        pf = proto.fields(f.get(3, [b""])[-1])
        return Part(
            index=f.get(1, [0])[-1],
            bytes_=f.get(2, [b""])[-1],
            proof=merkle.Proof(
                total=proto.as_sint64(pf.get(1, [0])[-1]),
                index=proto.as_sint64(pf.get(2, [0])[-1]),
                leaf_hash=pf.get(3, [b""])[-1],
                aunts=list(pf.get(4, [])),
            ),
        )


class PartSet:
    """Complete (from data) or incomplete (from header, filled by gossip)."""

    def __init__(self, header: PartSetHeader):
        self._header = header
        self.parts: list[Part | None] = [None] * header.total
        self.count = 0
        self.byte_size = 0

    @staticmethod
    def from_data(data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """reference: types/part_set.go NewPartSetFromData."""
        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = PartSet(PartSetHeader(total=len(chunks), hash=root))
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            ps.parts[i] = Part(index=i, bytes_=chunk, proof=proof)
        ps.count = len(chunks)
        ps.byte_size = len(data)
        return ps

    @staticmethod
    def from_header(header: PartSetHeader) -> "PartSet":
        return PartSet(header)

    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, header: PartSetHeader) -> bool:
        return self._header == header

    def add_part(self, part: Part) -> bool:
        """Verify + insert; False if duplicate (reference: types/part_set.go
        AddPart)."""
        if part.index >= self._header.total:
            raise ValueError("error part set unexpected index")
        if self.parts[part.index] is not None:
            return False
        part.proof.verify(self._header.hash, part.bytes_)
        self.parts[part.index] = part
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, index: int) -> Part | None:
        return self.parts[index]

    def is_complete(self) -> bool:
        return self.count == self._header.total

    def bit_array(self) -> list[bool]:
        return [p is not None for p in self.parts]

    def assemble(self) -> bytes:
        if not self.is_complete():
            raise ValueError("cannot assemble incomplete part set")
        return b"".join(p.bytes_ for p in self.parts)
