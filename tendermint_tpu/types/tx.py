"""Tx / Txs (reference: types/tx.go)."""

from __future__ import annotations

from tendermint_tpu.crypto import merkle, tmhash


def tx_hash(tx: bytes) -> bytes:
    """reference: types/tx.go:29 -- SHA-256 of the raw tx bytes."""
    return tmhash.sum(tx)


def tx_key(tx: bytes) -> bytes:
    return tmhash.sum(tx)


def txs_hash(txs: list[bytes]) -> bytes:
    """Merkle root over per-tx hashes (reference: types/tx.go:47-55).

    The per-tx leaves route through ops/chash.sha256_many when the C
    library is up, so a full block's tx hashing pays one FFI crossing
    instead of N hashlib calls — bit-identical either way (tmhash.sum IS
    SHA-256)."""
    if len(txs) > 1:
        from tendermint_tpu.ops import chash

        if chash.available():
            digests = chash.sha256_many(list(txs))
            return merkle.hash_from_byte_slices(
                [digests[i].tobytes() for i in range(len(txs))])
    return merkle.hash_from_byte_slices([tx_hash(t) for t in txs])


def txs_proof(txs: list[bytes], i: int):
    root, proofs = merkle.proofs_from_byte_slices([tx_hash(t) for t in txs])
    return root, proofs[i]


def compute_proto_size_overhead(field_count: int = 1) -> int:
    return field_count


def total_tx_bytes(txs: list[bytes]) -> int:
    """Wire size when embedded in Data (field 1, repeated bytes)."""
    from tendermint_tpu.encoding.proto import encode_uvarint

    return sum(1 + len(encode_uvarint(len(t))) + len(t) for t in txs)
