"""Block, Header, Data, Commit, CommitSig (reference: types/block.go:43,325,
575-787, proto/tendermint/types/types.proto).

Header.Hash is the Merkle root over the 14 proto-encoded fields in declaration
order (reference: types/block.go:440-476); scalar fields are wrapped in the
gogo well-known wrapper types first (cdcEncode, types/encoding_helper.go:11).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from tendermint_tpu.crypto import merkle, tmhash
from tendermint_tpu.encoding import proto
from tendermint_tpu.types import tx as tx_mod
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    PRECOMMIT_TYPE,
    Vote,
)

MAX_HEADER_BYTES = 626  # reference: types/block.go MaxHeaderBytes
BLOCK_PROTOCOL = 11  # reference: version/version.go:21


def cdc_encode_string(v: str) -> bytes:
    return cdc_encode_bytes(v.encode("utf-8")) if v else b""


def cdc_encode_int64(v: int) -> bytes:
    if not v:
        return b""
    return b"\x08" + proto.encode_varint(v)  # field 1, wire varint


def cdc_encode_bytes(v: bytes) -> bytes:
    if not v:
        return b""
    if len(v) < 0x80:  # field 1, wire bytes, single-byte length
        return b"\x0a" + bytes((len(v),)) + v
    return proto.Writer().bytes(1, v).out()


@dataclass(frozen=True)
class Consensus:
    """Version pair (reference: proto/tendermint/version/types.proto)."""

    block: int = BLOCK_PROTOCOL
    app: int = 0

    def marshal(self) -> bytes:
        return proto.Writer().uvarint(1, self.block).uvarint(2, self.app).out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Consensus":
        f = proto.fields(buf)
        return Consensus(block=f.get(1, [0])[-1], app=f.get(2, [0])[-1])


@dataclass
class Header:
    version: Consensus = dc_field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time: Time = dc_field(default_factory=Time.zero)
    last_block_id: BlockID = dc_field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    # Set only by precompute_header_hashes on finished headers.
    _hash_cache: bytes | None = dc_field(
        default=None, repr=False, compare=False)

    def __setattr__(self, name, value):
        # Invalidate the cached root on ANY later field mutation: a stale
        # hash() after mutation would silently corrupt block ids (round-4
        # advisor finding; previously safe only by caller convention).
        if name != "_hash_cache" and self.__dict__.get("_hash_cache") is not None:
            self.__dict__["_hash_cache"] = None
        object.__setattr__(self, name, value)

    def hash_fields(self) -> list[bytes]:
        """The 14 merkle leaves of the header hash
        (reference: types/block.go:440-476)."""
        return [
            self.version.marshal(),
            cdc_encode_string(self.chain_id),
            cdc_encode_int64(self.height),
            self.time.marshal(),
            self.last_block_id.marshal(),
            cdc_encode_bytes(self.last_commit_hash),
            cdc_encode_bytes(self.data_hash),
            cdc_encode_bytes(self.validators_hash),
            cdc_encode_bytes(self.next_validators_hash),
            cdc_encode_bytes(self.consensus_hash),
            cdc_encode_bytes(self.app_hash),
            cdc_encode_bytes(self.last_results_hash),
            cdc_encode_bytes(self.evidence_hash),
            cdc_encode_bytes(self.proposer_address),
        ]

    def hash(self) -> bytes | None:
        """reference: types/block.go:440-476. None when ValidatorsHash is
        unset (header not yet complete). Headers may be filled in
        incrementally, so the hash is NOT cached here — batch paths that
        hold finished headers use precompute_header_hashes."""
        if not self.validators_hash:
            return None
        if self._hash_cache is not None:
            return self._hash_cache
        return merkle.hash_from_byte_slices(self.hash_fields())

    def validate_basic(self) -> None:
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Header.Height")
        if self.height == 0:
            raise ValueError("zero Header.Height")
        self.last_block_id.validate_basic()
        for name in ("last_commit_hash", "data_hash", "evidence_hash",
                     "validators_hash", "next_validators_hash",
                     "consensus_hash", "last_results_hash"):
            h = getattr(self, name)
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"wrong {name}")
        if len(self.proposer_address) != 20:
            raise ValueError("invalid ProposerAddress length")

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .message(1, self.version.marshal(), always=True)
            .string(2, self.chain_id)
            .varint(3, self.height)
            .message(4, self.time.marshal(), always=True)
            .message(5, self.last_block_id.marshal(), always=True)
            .bytes(6, self.last_commit_hash)
            .bytes(7, self.data_hash)
            .bytes(8, self.validators_hash)
            .bytes(9, self.next_validators_hash)
            .bytes(10, self.consensus_hash)
            .bytes(11, self.app_hash)
            .bytes(12, self.last_results_hash)
            .bytes(13, self.evidence_hash)
            .bytes(14, self.proposer_address)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Header":
        f = proto.fields(buf)
        return Header(
            version=Consensus.unmarshal(f.get(1, [b""])[-1]),
            chain_id=f.get(2, [b""])[-1].decode("utf-8"),
            height=proto.as_sint64(f.get(3, [0])[-1]),
            time=Time.unmarshal(f.get(4, [b""])[-1]),
            last_block_id=BlockID.unmarshal(f.get(5, [b""])[-1]),
            last_commit_hash=f.get(6, [b""])[-1],
            data_hash=f.get(7, [b""])[-1],
            validators_hash=f.get(8, [b""])[-1],
            next_validators_hash=f.get(9, [b""])[-1],
            consensus_hash=f.get(10, [b""])[-1],
            app_hash=f.get(11, [b""])[-1],
            last_results_hash=f.get(12, [b""])[-1],
            evidence_hash=f.get(13, [b""])[-1],
            proposer_address=f.get(14, [b""])[-1],
        )


def precompute_header_hashes(headers: list[Header]) -> None:
    """Hash a whole header chain as one same-arity merkle forest
    (crypto/merkle hash_trees_fixed: O(log 14) C-batched sha256 calls
    instead of 27 hashlib calls per header) and fill each header's hash
    cache. Only finished headers (validators_hash set) are cached; call
    this on received chains, never on headers still being built."""
    done = [h for h in headers
            if h.validators_hash and h._hash_cache is None]
    if not done:
        return
    roots = merkle.hash_trees_fixed([h.hash_fields() for h in done])
    for h, root in zip(done, roots):
        h._hash_cache = root


@dataclass
class CommitSig:
    """One validator's slot in a Commit (reference: types/block.go:575-680)."""

    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp: Time = dc_field(default_factory=Time.zero)
    signature: bytes = b""

    @staticmethod
    def new_absent() -> "CommitSig":
        return CommitSig()

    @staticmethod
    def new_commit(block_id_flag: int, validator_address: bytes,
                   timestamp: Time, signature: bytes) -> "CommitSig":
        return CommitSig(block_id_flag, validator_address, timestamp, signature)

    def absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """reference: types/block.go:652-665."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> None:
        if self.block_id_flag not in (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.absent():
            if self.validator_address:
                raise ValueError("validator address is present")
            if not self.timestamp.is_zero():
                raise ValueError("time is present")
            if self.signature:
                raise ValueError("signature is present")
        else:
            if len(self.validator_address) != 20:
                raise ValueError("expected ValidatorAddress size to be 20 bytes")
            if not self.signature:
                raise ValueError("signature is missing")
            if len(self.signature) > 64:
                raise ValueError("signature is too big")

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .varint(1, self.block_id_flag)
            .bytes(2, self.validator_address)
            .message(3, self.timestamp.marshal(), always=True)
            .bytes(4, self.signature)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "CommitSig":
        f = proto.fields(buf)
        return CommitSig(
            block_id_flag=f.get(1, [0])[-1],
            validator_address=f.get(2, [b""])[-1],
            timestamp=Time.unmarshal(f.get(3, [b""])[-1]),
            signature=f.get(4, [b""])[-1],
        )


@dataclass
class Commit:
    height: int = 0
    round: int = 0
    block_id: BlockID = dc_field(default_factory=BlockID)
    signatures: list[CommitSig] = dc_field(default_factory=list)

    def get_vote(self, val_idx: int) -> Vote:
        """Reconstruct the precommit Vote for validator slot val_idx
        (reference: types/block.go:784-806)."""
        cs = self.signatures[val_idx]
        return Vote(
            type=PRECOMMIT_TYPE,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp=cs.timestamp,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Canonical sign bytes for the precommit in slot val_idx —
        equivalent to get_vote(val_idx).sign_bytes(chain_id) (differential-
        tested). Rides canonical_vote_bytes' template cache, so
        verify_commit-style loops pay one Writer build per (commit, flag)
        instead of one per vote."""
        from tendermint_tpu.types.vote import canonical_vote_bytes

        cs = self.signatures[val_idx]
        return canonical_vote_bytes(chain_id, PRECOMMIT_TYPE, self.height,
                                    self.round, cs.block_id(self.block_id),
                                    cs.timestamp)

    def size(self) -> int:
        return len(self.signatures)

    def is_commit(self) -> bool:
        return len(self.signatures) != 0

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}") from e

    def hash(self) -> bytes:
        """reference: types/block.go:894-911."""
        return merkle.hash_from_byte_slices([cs.marshal() for cs in self.signatures])

    def bit_array(self) -> list[bool]:
        return [not cs.absent() for cs in self.signatures]

    def marshal(self) -> bytes:
        w = (
            proto.Writer()
            .varint(1, self.height)
            .varint(2, self.round)
            .message(3, self.block_id.marshal(), always=True)
        )
        for cs in self.signatures:
            w.message(4, cs.marshal(), always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Commit":
        f = proto.fields(buf)
        return Commit(
            height=proto.as_sint64(f.get(1, [0])[-1]),
            round=proto.as_sint64(f.get(2, [0])[-1]),
            block_id=BlockID.unmarshal(f.get(3, [b""])[-1]),
            signatures=[CommitSig.unmarshal(b) for b in f.get(4, [])],
        )


@dataclass
class Data:
    txs: list[bytes] = dc_field(default_factory=list)

    def hash(self) -> bytes:
        return tx_mod.txs_hash(self.txs)

    def marshal(self) -> bytes:
        w = proto.Writer()
        for t in self.txs:
            w.bytes(1, t) if t else w.message(1, b"", always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Data":
        f = proto.fields(buf)
        return Data(txs=list(f.get(1, [])))


@dataclass
class Block:
    header: Header = dc_field(default_factory=Header)
    data: Data = dc_field(default_factory=Data)
    evidence: list = dc_field(default_factory=list)
    last_commit: Commit | None = None

    def hash(self) -> bytes | None:
        """Header hash, with LastCommitHash filled (reference:
        types/block.go:123-141 fillHeader + Hash)."""
        if self.last_commit is None and self.header.height > 1:
            return None
        self.fill_header()
        return self.header.hash()

    def fill_header(self) -> None:
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = evidence_hash(self.evidence)

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.last_commit is None and self.header.height > 1:
            raise ValueError("nil LastCommit")
        if self.last_commit is not None:
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("wrong Header.LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong Header.DataHash")
        if self.header.evidence_hash != evidence_hash(self.evidence):
            raise ValueError("wrong Header.EvidenceHash")

    def hashes_to(self, h: bytes) -> bool:
        return bool(h) and self.hash() == h

    def marshal(self) -> bytes:
        w = (
            proto.Writer()
            .message(1, self.header.marshal(), always=True)
            .message(2, self.data.marshal(), always=True)
            .message(3, evidence_list_marshal(self.evidence), always=True)
        )
        if self.last_commit is not None:
            w.message(4, self.last_commit.marshal())
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Block":
        from tendermint_tpu.types import evidence as ev_mod

        f = proto.fields(buf)
        evs = []
        if 3 in f:
            ef = proto.fields(f[3][-1])
            evs = [ev_mod.evidence_unmarshal(b) for b in ef.get(1, [])]
        lc = Commit.unmarshal(f[4][-1]) if 4 in f else None
        return Block(
            header=Header.unmarshal(f.get(1, [b""])[-1]),
            data=Data.unmarshal(f.get(2, [b""])[-1]),
            evidence=evs,
            last_commit=lc,
        )


def evidence_hash(evidence: list) -> bytes:
    """EvidenceData hash = merkle over evidence proto marshals (reference:
    types/evidence.go EvidenceData/evidence list Hash)."""
    return merkle.hash_from_byte_slices([ev.bytes() for ev in evidence])


def evidence_list_marshal(evidence: list) -> bytes:
    w = proto.Writer()
    for ev in evidence:
        w.message(1, ev.bytes(), always=True)
    return w.out()


def make_commit(block_id: BlockID, height: int, round_: int, votes) -> Commit:
    """Build a Commit from a VoteSet's ordered vote slots (reference:
    types/vote_set.go:612-636 MakeCommit + types/vote.go:62 CommitSig): a
    vote for a block OTHER than the maj23 block is excluded (absent), not
    marked nil -- its signature signs a different BlockID."""
    sigs = []
    for v in votes:
        if v is None:
            sigs.append(CommitSig.new_absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if v.block_id.is_zero() else BLOCK_ID_FLAG_COMMIT
        if flag == BLOCK_ID_FLAG_COMMIT and v.block_id != block_id:
            sigs.append(CommitSig.new_absent())
            continue
        sigs.append(CommitSig(flag, v.validator_address, v.timestamp, v.signature))
    return Commit(height=height, round=round_, block_id=block_id, signatures=sigs)
