"""Proposal and its canonical sign-bytes (reference: types/proposal.go,
types/canonical.go:41, proto/tendermint/types/canonical.proto)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import PROPOSAL_TYPE, canonical_block_id_bytes


def canonical_proposal_bytes(chain_id: str, height: int, round_: int,
                             pol_round: int, block_id: BlockID,
                             timestamp: Time) -> bytes:
    w = proto.Writer()
    w.varint(1, PROPOSAL_TYPE)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.varint(4, pol_round)
    cbid = canonical_block_id_bytes(block_id)
    if cbid is not None:
        w.message(5, cbid, always=True)
    w.message(6, timestamp.marshal(), always=True)
    w.string(7, chain_id)
    return proto.delimited(w.out())


@dataclass
class Proposal:
    type: int = PROPOSAL_TYPE
    height: int = 0
    round: int = 0
    pol_round: int = -1
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Time = field(default_factory=Time.zero)
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_proposal_bytes(
            chain_id, self.height, self.round, self.pol_round, self.block_id, self.timestamp
        )

    def validate_basic(self) -> None:
        if self.type != PROPOSAL_TYPE:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1:
            raise ValueError("negative POLRound (exception: -1)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("expected a complete, non-empty BlockID")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > 64:
            raise ValueError("signature is too big")

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .varint(1, self.type)
            .varint(2, self.height)
            .varint(3, self.round)
            .varint(4, self.pol_round)
            .message(5, self.block_id.marshal(), always=True)
            .message(6, self.timestamp.marshal(), always=True)
            .bytes(7, self.signature)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "Proposal":
        f = proto.fields(buf)
        return Proposal(
            type=f.get(1, [PROPOSAL_TYPE])[-1],
            height=proto.as_sint64(f.get(2, [0])[-1]),
            round=proto.as_sint64(f.get(3, [0])[-1]),
            pol_round=proto.as_sint64(f.get(4, [0])[-1]),
            block_id=BlockID.unmarshal(f.get(5, [b""])[-1]),
            timestamp=Time.unmarshal(f.get(6, [b""])[-1]),
            signature=f.get(7, [b""])[-1],
        )

    def __str__(self) -> str:
        return (
            f"Proposal{{{self.height}/{self.round} ({self.block_id}, "
            f"{self.pol_round}) {self.signature.hex()[:12]} @ {self.timestamp}}}"
        )
