"""BlockID and PartSetHeader (reference: types/block.go:1112-1180,
proto/tendermint/types/types.proto BlockID/PartSetHeader)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.encoding import proto


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong PartSetHeader hash size")

    def marshal(self) -> bytes:
        return proto.Writer().uvarint(1, self.total).bytes(2, self.hash).out()

    @staticmethod
    def unmarshal(buf: bytes) -> "PartSetHeader":
        f = proto.fields(buf)
        return PartSetHeader(
            total=f.get(1, [0])[-1], hash=f.get(2, [b""])[-1]
        )


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        """Nil-block marker (a vote for nil)."""
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        """Points to a real block."""
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong BlockID hash size")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key (reference: types/block.go BlockID.Key). Cached: the
        consensus hot path calls key() several times per vote, and both
        fields are immutable (frozen dataclass, bytes)."""
        k = self.__dict__.get("_key")
        if k is None:
            k = self.hash + self.part_set_header.marshal()
            object.__setattr__(self, "_key", k)
        return k

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .bytes(1, self.hash)
            .message(2, self.part_set_header.marshal(), always=True)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "BlockID":
        f = proto.fields(buf)
        psh = PartSetHeader.unmarshal(f.get(2, [b""])[-1])
        return BlockID(hash=f.get(1, [b""])[-1], part_set_header=psh)

    def __str__(self) -> str:
        return f"{self.hash.hex()[:12]}:{self.part_set_header.total}"
