"""Operator CLI (reference: cmd/tendermint/commands/): init, start, testnet,
show-node-id, show-validator, gen-validator, gen-node-key, unsafe-reset-all,
rollback, replay, version.

Usage: python -m tendermint_tpu.cli <command> [--home DIR] [options]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

from tendermint_tpu.config.config import Config, default_config


def _home(args) -> str:
    return os.path.abspath(args.home or os.environ.get("TMTPU_HOME", os.path.expanduser("~/.tendermint-tpu")))


def _ensure_dirs(root: str) -> None:
    for d in ("config", "data"):
        os.makedirs(os.path.join(root, d), exist_ok=True)


def _load_config(root: str) -> Config:
    cfg = default_config().set_root(root)
    toml_path = os.path.join(root, "config", "config.toml")
    if os.path.exists(toml_path):
        from tendermint_tpu.config.toml import load_toml_into

        load_toml_into(cfg, toml_path)
    cfg.base.root_dir = root
    return cfg


def cmd_init(args) -> int:
    """reference: cmd/tendermint/commands/init.go."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    root = _home(args)
    _ensure_dirs(root)
    cfg = default_config().set_root(root)

    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_gen(cfg.node_key_file())

    gen_file = cfg.genesis_file()
    if os.path.exists(gen_file):
        print(f"Found genesis file {gen_file}")
    else:
        chain_id = args.chain_id or f"test-chain-{os.urandom(3).hex()}"
        doc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Time.now(),
            validators=[GenesisValidator(b"", pv.get_pub_key(), 10)],
        )
        doc.validate_and_complete()
        doc.save_as(gen_file)
        print(f"Generated genesis file {gen_file}")

    from tendermint_tpu.config.toml import write_config_toml

    toml_path = os.path.join(root, "config", "config.toml")
    if not os.path.exists(toml_path):
        write_config_toml(cfg, toml_path)
        print(f"Generated config file {toml_path}")
    return 0


def cmd_start(args) -> int:
    """reference: cmd/tendermint/commands/run_node.go."""
    from tendermint_tpu.node.node import Node

    root = _home(args)
    cfg = _load_config(root)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers

    node = Node(cfg)
    mb = os.environ.get("TMTPU_BYZ") or os.environ.get("TMTPU_MISBEHAVIOR")
    if mb:
        # e2e byzantine node (reference: test/maverick); TMTPU_BYZ takes a
        # full height-windowed behavior spec (docs/BYZANTINE.md), the
        # legacy TMTPU_MISBEHAVIOR a bare behavior name; honest peers must
        # detect what is detectable and keep committing.
        node.install_misbehavior(mb)
    node.start()
    print(f"Started node {node.node_key.id()} p2p={node.transport.node_info.listen_addr}")

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from tendermint_tpu.p2p.key import NodeKey

    cfg = _load_config(_home(args))
    print(NodeKey.load(cfg.node_key_file()).id())
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from tendermint_tpu.privval.file_pv import FilePV

    cfg = _load_config(_home(args))
    pv = FilePV.load(cfg.priv_validator_key_file(), cfg.priv_validator_state_file())
    pub = pv.get_pub_key()
    print(json.dumps({"type": "tendermint/PubKeyEd25519",
                      "value": base64.b64encode(pub.bytes()).decode()}))
    return 0


def cmd_gen_validator(args) -> int:
    import base64

    from tendermint_tpu.crypto import ed25519

    priv = ed25519.gen_priv_key()
    print(json.dumps({
        "address": priv.pub_key().address().hex().upper(),
        "pub_key": {"type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(priv.pub_key().bytes()).decode()},
        "priv_key": {"type": "tendermint/PrivKeyEd25519",
                     "value": base64.b64encode(priv.bytes()).decode()},
    }, indent=2))
    return 0


def cmd_gen_node_key(args) -> int:
    from tendermint_tpu.p2p.key import NodeKey

    cfg = _load_config(_home(args))
    nk = NodeKey.load_or_gen(cfg.node_key_file())
    print(nk.id())
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """reference: cmd/tendermint/commands/reset.go."""
    root = _home(args)
    data = os.path.join(root, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
        os.makedirs(data)
    # keep the validator key; reset sign state
    from tendermint_tpu.privval.file_pv import FilePV

    cfg = default_config().set_root(root)
    if os.path.exists(cfg.priv_validator_key_file()):
        pv = FilePV.load(cfg.priv_validator_key_file(), cfg.priv_validator_state_file())
        pv.last_sign_state.save()
    print(f"Reset {data}")
    return 0


def cmd_testnet(args) -> int:
    """Generate a v-node localnet layout (reference:
    cmd/tendermint/commands/testnet.go)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.config.toml import write_config_toml

    out = os.path.abspath(args.output)
    n = args.v
    pvs = []
    node_keys = []
    for i in range(n):
        root = os.path.join(out, f"node{i}")
        _ensure_dirs(root)
        cfg = default_config().set_root(root)
        pvs.append(FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                           cfg.priv_validator_state_file()))
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_file()))

    doc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=Time.now(),
        validators=[GenesisValidator(b"", pv.get_pub_key(), 1) for pv in pvs],
    )
    doc.validate_and_complete()

    peers = ",".join(
        f"{node_keys[i].id()}@127.0.0.1:{args.starting_port + 2 * i}" for i in range(n)
    )
    for i in range(n):
        root = os.path.join(out, f"node{i}")
        cfg = default_config().set_root(root)
        doc.save_as(cfg.genesis_file())
        cfg.p2p.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i + 1}"
        cfg.p2p.persistent_peers = peers
        write_config_toml(cfg, os.path.join(root, "config", "config.toml"))
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_rollback(args) -> int:
    """Undo one height (reference: cmd/tendermint/commands/rollback.go,
    state/rollback.go:112)."""
    from tendermint_tpu.state.rollback import rollback_state

    cfg = _load_config(_home(args))
    height, app_hash = rollback_state(cfg)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_version(args) -> int:
    print("0.34.24-tpu")
    return 0


def cmd_light(args) -> int:
    """Light client daemon: track a chain over RPC with verified headers and
    serve verified light blocks (reference: cmd/tendermint/commands/light.go).
    """
    from tendermint_tpu.light import (
        Client,
        DBStore,
        HTTPProvider,
        TrustOptions,
    )
    from tendermint_tpu.store.db import new_db
    from tendermint_tpu.types.ttime import Time

    root = _home(args)
    _ensure_dirs(root)
    chain_id = args.chain_id
    primary = HTTPProvider(chain_id, args.primary)
    witnesses = [HTTPProvider(chain_id, w) for w in args.witnesses.split(",") if w]
    store = DBStore(new_db("sqlite", os.path.join(root, "data", "light.db")))
    if bool(args.trust_height) != bool(args.trust_hash):
        # Half an anchor is no anchor: silently falling back to TOFU would
        # discard the operator's pin (reference light.go requires both).
        print("error: --trusted-height and --trusted-hash must be given together",
              file=sys.stderr)
        return 1
    if args.trust_height and args.trust_hash:
        opts = TrustOptions(period_s=args.trust_period, height=args.trust_height,
                            hash=bytes.fromhex(args.trust_hash))
    else:
        # TOFU bootstrap from the primary's latest header
        lb = primary.light_block(0)
        opts = TrustOptions(period_s=args.trust_period, height=lb.height,
                            hash=lb.hash())
        print(f"Trusting height {lb.height} hash {lb.hash().hex().upper()} (TOFU)")
    client = Client(chain_id, opts, primary, witnesses, store,
                    max_clock_drift_s=120.0)
    print(f"Light client running against {args.primary} "
          f"(latest trusted: {client.latest_trusted.height})")
    proxy = None
    if args.laddr:
        from tendermint_tpu.light.proxy import LightProxy

        proxy = LightProxy(client, args.primary, args.laddr)
        proxy.start()
        print(f"Verifying proxy listening on {proxy.laddr}")
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        try:
            lb = client.update(Time.now())
            if lb is not None:
                print(f"verified height {lb.height} "
                      f"hash {lb.hash().hex().upper()[:16]}...")
        except Exception as e:  # noqa: BLE001
            print(f"update failed: {e}", file=sys.stderr)
        if args.once:
            break
        time.sleep(args.interval)
    if proxy is not None:
        proxy.stop()
    return 0


def cmd_signer_harness(args) -> int:
    """Operator tool: validate a remote signer deployment (reference:
    tools/tm-signer-harness, docs/tools/remote-signer-validation.md)."""
    from tendermint_tpu.privval.harness import run_harness, summary_json

    code = run_harness(args.addr, args.chain_id, home=args.home,
                       accept_timeout_s=args.accept_timeout)
    print(summary_json(code))
    return code


def cmd_replay(args) -> int:
    """Replay the block store through a fresh app and report the final state
    (reference: cmd/tendermint/commands/replay.go + consensus/replay_file.go).
    """
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.node.node import default_app
    from tendermint_tpu.abci.proxy import new_app_conns
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.store.db import new_db
    from tendermint_tpu.types.genesis import GenesisDoc

    cfg = _load_config(_home(args))
    dbdir = cfg.db_dir()
    block_store = BlockStore(new_db(cfg.base.db_backend,
                                    os.path.join(dbdir, "blockstore.db")))
    state_store = StateStore(new_db(cfg.base.db_backend,
                                    os.path.join(dbdir, "state.db")))
    genesis = GenesisDoc.from_file(cfg.genesis_file())
    state = state_store.load()
    proxy = new_app_conns(default_app(cfg.base.proxy_app))
    hs = Handshaker(state_store, block_store, genesis)
    new_state = hs.handshake(state, proxy.consensus)
    print(f"Replayed to height {new_state.last_block_height} "
          f"app_hash {new_state.app_hash.hex().upper()}")
    return 0


def cmd_reindex_event(args) -> int:
    """Rebuild the tx/block index from the block store + stored ABCI
    responses (reference: cmd/tendermint/commands/reindex_event.go)."""
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.state.txindex import BlockIndexer, TxIndexer
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.store.db import new_db

    cfg = _load_config(_home(args))
    dbdir = cfg.db_dir()
    block_store = BlockStore(new_db(cfg.base.db_backend,
                                    os.path.join(dbdir, "blockstore.db")))
    state_store = StateStore(new_db(cfg.base.db_backend,
                                    os.path.join(dbdir, "state.db")))
    idx_db = new_db(cfg.base.db_backend, os.path.join(dbdir, "tx_index.db"))
    txi, bi = TxIndexer(idx_db), BlockIndexer(idx_db)
    start = args.start_height or block_store.base
    end = args.end_height or block_store.height
    n_txs = 0
    skipped = []
    for h in range(start, end + 1):
        block = block_store.load_block(h)
        if block is None:
            continue
        try:
            resp = state_store.load_abci_responses(h)
        except Exception:  # noqa: BLE001 - pruned responses
            # Never index fabricated results (the reference aborts here);
            # skip the height and tell the operator.
            skipped.append(h)
            continue
        deliver = resp.deliver_txs
        for i, tx in enumerate(block.data.txs):
            if i >= len(deliver):
                break
            txi.index(h, i, tx, deliver[i])
            n_txs += 1
        bi.index(h, resp.begin_block.events if resp.begin_block else [],
                 resp.end_block.events if resp.end_block else [])
    print(f"Reindexed heights {start}..{end}: {n_txs} txs"
          + (f"; skipped {len(skipped)} heights with pruned ABCI responses"
             if skipped else ""))
    return 0


def cmd_compact(args) -> int:
    """Compact the sqlite databases (reference:
    cmd/tendermint/commands/compact.go for goleveldb)."""
    import sqlite3

    cfg = _load_config(_home(args))
    if cfg.base.db_backend != "sqlite":
        print(f"nothing to compact for backend {cfg.base.db_backend!r}")
        return 0
    for name in os.listdir(cfg.db_dir()):
        if not name.endswith(".db"):
            continue
        path = os.path.join(cfg.db_dir(), name)
        before = os.path.getsize(path)
        conn = sqlite3.connect(path)
        conn.execute("VACUUM")
        conn.close()
        print(f"compacted {name}: {before} -> {os.path.getsize(path)} bytes")
    return 0


def cmd_debug(args) -> int:
    """Dump node state for debugging (reference:
    cmd/tendermint/commands/debug/dump.go): config, stores summary, and
    (when the node is running) /status + /dump_consensus_state via RPC."""
    import urllib.request

    cfg = _load_config(_home(args))
    out_dir = args.output or os.path.join(_home(args), "debug")
    os.makedirs(out_dir, exist_ok=True)
    doc = {"home": _home(args), "db_backend": cfg.base.db_backend}
    try:
        from tendermint_tpu.store.block_store import BlockStore
        from tendermint_tpu.store.db import new_db

        bs = BlockStore(new_db(cfg.base.db_backend,
                               os.path.join(cfg.db_dir(), "blockstore.db")))
        doc["block_store"] = {"base": bs.base, "height": bs.height}
    except Exception as e:  # noqa: BLE001
        doc["block_store"] = {"error": str(e)}
    if args.rpc_laddr:
        base = "http://" + args.rpc_laddr.split("://", 1)[-1]
        for method in ("status", "dump_consensus_state", "net_info"):
            try:
                body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                                   "params": {}}).encode()
                with urllib.request.urlopen(urllib.request.Request(
                        base, data=body,
                        headers={"Content-Type": "application/json"}),
                        timeout=5) as r:
                    doc[method] = json.loads(r.read()).get("result")
            except Exception as e:  # noqa: BLE001
                doc[method] = {"error": str(e)}
    path = os.path.join(out_dir, "dump.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    print(f"wrote {path}")
    return 0


def cmd_probe_upnp(args) -> int:
    """Probe for a UPnP gateway (reference: cmd/tendermint/commands/
    probe_upnp.go)."""
    from tendermint_tpu.p2p import upnp

    try:
        out = upnp.probe(timeout_s=args.timeout)
    except upnp.UPnPError as e:
        print(f"Probe failed: {e}")
        return 1
    print(json.dumps(out, indent=2))
    return 0


def cmd_abci_server(args) -> int:
    """Run an example app behind an ABCI socket (reference:
    abci/cmd/abci-cli: kvstore and counter subcommands)."""
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.store.db import new_db

    if args.app == "counter":
        from tendermint_tpu.abci.counter import CounterApp

        if args.db or args.snapshot_interval:
            print("abci-server: --db/--snapshot-interval apply only to "
                  "kvstore", file=sys.stderr)
            return 1
        app = CounterApp(serial=args.serial)
    else:
        from tendermint_tpu.abci.kvstore import KVStoreApplication

        db = new_db("sqlite", args.db) if args.db else None
        app = KVStoreApplication(db, snapshot_interval=args.snapshot_interval)
    server = ABCIServer(app, args.address)
    server.start()
    print(f"ABCI {args.app} server listening on {server.addr}")
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        server.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint-tpu")
    p.add_argument("--home", default=None, help="node home directory")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize a node")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.persistent_peers", dest="persistent_peers", default="")
    sp.set_defaults(fn=cmd_start)

    for name, fn in (("show-node-id", cmd_show_node_id),
                     ("show-validator", cmd_show_validator),
                     ("gen-validator", cmd_gen_validator),
                     ("gen-node-key", cmd_gen_node_key),
                     ("unsafe-reset-all", cmd_unsafe_reset_all),
                     ("rollback", cmd_rollback),
                     ("version", cmd_version)):
        sp = sub.add_parser(name)
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("testnet", help="generate a localnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output", "-o", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("light", help="run a light client daemon")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", "-p", required=True, help="primary RPC address")
    sp.add_argument("--witnesses", "-w", default="", help="comma-separated witness RPC addresses")
    sp.add_argument("--trusted-height", dest="trust_height", type=int, default=0)
    sp.add_argument("--trusted-hash", dest="trust_hash", default="")
    sp.add_argument("--trust-period", dest="trust_period", type=float,
                    default=168 * 3600.0)
    sp.add_argument("--interval", type=float, default=1.0)
    sp.add_argument("--once", action="store_true", help="single update then exit")
    sp.add_argument("--laddr", default="",
                    help="serve a verifying RPC proxy on this address")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser(
        "signer-harness",
        help="validate a remote signer deployment (reference: "
             "tools/tm-signer-harness)")
    sp.add_argument("--addr", required=True,
                    help="listen address the remote signer dials, e.g. "
                         "tcp://127.0.0.1:26659")
    sp.add_argument("--chain-id", required=True)
    sp.add_argument("--accept-timeout", type=float, default=30.0)
    sp.set_defaults(fn=cmd_signer_harness)

    sp = sub.add_parser("replay", help="replay the block store through the app")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("reindex-event", help="rebuild the tx/block index")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser("compact", help="compact the node databases")
    sp.set_defaults(fn=cmd_compact)

    sp = sub.add_parser("debug", help="dump node state for debugging")
    sp.add_argument("--output", default="")
    sp.add_argument("--rpc-laddr", default="", help="running node RPC to query")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("probe-upnp", help="probe for a UPnP gateway")
    sp.add_argument("--timeout", type=float, default=3.0)
    sp.set_defaults(fn=cmd_probe_upnp)

    sp = sub.add_parser("abci-server", help="run an example app behind a socket")
    sp.add_argument("--address", default="tcp://127.0.0.1:26658")
    sp.add_argument("--app", default="kvstore", choices=["kvstore", "counter"])
    sp.add_argument("--serial", action="store_true",
                    help="counter: enforce serial nonces")
    sp.add_argument("--db", default="", help="sqlite path for persistence")
    sp.add_argument("--snapshot-interval", type=int, default=0)
    sp.set_defaults(fn=cmd_abci_server)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
