"""Operator CLI (reference: cmd/tendermint/commands/): init, start, testnet,
show-node-id, show-validator, gen-validator, gen-node-key, unsafe-reset-all,
rollback, replay, version.

Usage: python -m tendermint_tpu.cli <command> [--home DIR] [options]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

from tendermint_tpu.config.config import Config, default_config


def _home(args) -> str:
    return os.path.abspath(args.home or os.environ.get("TMTPU_HOME", os.path.expanduser("~/.tendermint-tpu")))


def _ensure_dirs(root: str) -> None:
    for d in ("config", "data"):
        os.makedirs(os.path.join(root, d), exist_ok=True)


def _load_config(root: str) -> Config:
    cfg = default_config().set_root(root)
    toml_path = os.path.join(root, "config", "config.toml")
    if os.path.exists(toml_path):
        from tendermint_tpu.config.toml import load_toml_into

        load_toml_into(cfg, toml_path)
    cfg.base.root_dir = root
    return cfg


def cmd_init(args) -> int:
    """reference: cmd/tendermint/commands/init.go."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    root = _home(args)
    _ensure_dirs(root)
    cfg = default_config().set_root(root)

    pv = FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                 cfg.priv_validator_state_file())
    NodeKey.load_or_gen(cfg.node_key_file())

    gen_file = cfg.genesis_file()
    if os.path.exists(gen_file):
        print(f"Found genesis file {gen_file}")
    else:
        chain_id = args.chain_id or f"test-chain-{os.urandom(3).hex()}"
        doc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Time.now(),
            validators=[GenesisValidator(b"", pv.get_pub_key(), 10)],
        )
        doc.validate_and_complete()
        doc.save_as(gen_file)
        print(f"Generated genesis file {gen_file}")

    from tendermint_tpu.config.toml import write_config_toml

    toml_path = os.path.join(root, "config", "config.toml")
    if not os.path.exists(toml_path):
        write_config_toml(cfg, toml_path)
        print(f"Generated config file {toml_path}")
    return 0


def cmd_start(args) -> int:
    """reference: cmd/tendermint/commands/run_node.go."""
    from tendermint_tpu.node.node import Node

    root = _home(args)
    cfg = _load_config(root)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers

    node = Node(cfg)
    node.start()
    print(f"Started node {node.node_key.id()} p2p={node.transport.node_info.listen_addr}")

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from tendermint_tpu.p2p.key import NodeKey

    cfg = _load_config(_home(args))
    print(NodeKey.load(cfg.node_key_file()).id())
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from tendermint_tpu.privval.file_pv import FilePV

    cfg = _load_config(_home(args))
    pv = FilePV.load(cfg.priv_validator_key_file(), cfg.priv_validator_state_file())
    pub = pv.get_pub_key()
    print(json.dumps({"type": "tendermint/PubKeyEd25519",
                      "value": base64.b64encode(pub.bytes()).decode()}))
    return 0


def cmd_gen_validator(args) -> int:
    import base64

    from tendermint_tpu.crypto import ed25519

    priv = ed25519.gen_priv_key()
    print(json.dumps({
        "address": priv.pub_key().address().hex().upper(),
        "pub_key": {"type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(priv.pub_key().bytes()).decode()},
        "priv_key": {"type": "tendermint/PrivKeyEd25519",
                     "value": base64.b64encode(priv.bytes()).decode()},
    }, indent=2))
    return 0


def cmd_gen_node_key(args) -> int:
    from tendermint_tpu.p2p.key import NodeKey

    cfg = _load_config(_home(args))
    nk = NodeKey.load_or_gen(cfg.node_key_file())
    print(nk.id())
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """reference: cmd/tendermint/commands/reset.go."""
    root = _home(args)
    data = os.path.join(root, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
        os.makedirs(data)
    # keep the validator key; reset sign state
    from tendermint_tpu.privval.file_pv import FilePV

    cfg = default_config().set_root(root)
    if os.path.exists(cfg.priv_validator_key_file()):
        pv = FilePV.load(cfg.priv_validator_key_file(), cfg.priv_validator_state_file())
        pv.last_sign_state.save()
    print(f"Reset {data}")
    return 0


def cmd_testnet(args) -> int:
    """Generate a v-node localnet layout (reference:
    cmd/tendermint/commands/testnet.go)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.config.toml import write_config_toml

    out = os.path.abspath(args.output)
    n = args.v
    pvs = []
    node_keys = []
    for i in range(n):
        root = os.path.join(out, f"node{i}")
        _ensure_dirs(root)
        cfg = default_config().set_root(root)
        pvs.append(FilePV.load_or_generate(cfg.priv_validator_key_file(),
                                           cfg.priv_validator_state_file()))
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_file()))

    doc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=Time.now(),
        validators=[GenesisValidator(b"", pv.get_pub_key(), 1) for pv in pvs],
    )
    doc.validate_and_complete()

    peers = ",".join(
        f"{node_keys[i].id()}@127.0.0.1:{args.starting_port + 2 * i}" for i in range(n)
    )
    for i in range(n):
        root = os.path.join(out, f"node{i}")
        cfg = default_config().set_root(root)
        doc.save_as(cfg.genesis_file())
        cfg.p2p.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i + 1}"
        cfg.p2p.persistent_peers = peers
        write_config_toml(cfg, os.path.join(root, "config", "config.toml"))
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_rollback(args) -> int:
    """Undo one height (reference: cmd/tendermint/commands/rollback.go,
    state/rollback.go:112)."""
    from tendermint_tpu.state.rollback import rollback_state

    cfg = _load_config(_home(args))
    height, app_hash = rollback_state(cfg)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_version(args) -> int:
    print("0.34.24-tpu")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint-tpu")
    p.add_argument("--home", default=None, help="node home directory")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize a node")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.persistent_peers", dest="persistent_peers", default="")
    sp.set_defaults(fn=cmd_start)

    for name, fn in (("show-node-id", cmd_show_node_id),
                     ("show-validator", cmd_show_validator),
                     ("gen-validator", cmd_gen_validator),
                     ("gen-node-key", cmd_gen_node_key),
                     ("unsafe-reset-all", cmd_unsafe_reset_all),
                     ("rollback", cmd_rollback),
                     ("version", cmd_version)):
        sp = sub.add_parser(name)
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("testnet", help="generate a localnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output", "-o", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
