import sys

from tendermint_tpu.cli.main import main

sys.exit(main())
