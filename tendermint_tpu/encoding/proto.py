"""Minimal protobuf wire codec.

Byte-compatible with the gogoproto-generated marshaling the reference uses for
its canonical sign-bytes and wire types (reference: proto/tendermint/types/
canonical.proto, libs/protoio/writer.go). We implement only the wire format —
varint, fixed64/32, length-delimited — plus the delimited (varint length
prefixed) framing `protoio.MarshalDelimited` applies to sign-bytes
(reference: types/vote.go:93, libs/protoio/io.go).

proto3 zero-value omission rules are applied by the callers (message builders
in tendermint_tpu.encoding.canonical and tendermint_tpu.types): scalar fields
equal to zero / empty are omitted; non-nullable embedded messages are always
emitted (gogoproto.nullable=false semantics).
"""

from __future__ import annotations

import struct

# Wire types
WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


_UV1 = tuple(bytes((i,)) for i in range(0x80))


def encode_uvarint(n: int) -> bytes:
    if 0 <= n < 0x80:  # single-byte fast path (tags, lengths, small ints)
        return _UV1[n]
    if n < 0:
        raise ValueError("uvarint cannot be negative")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_varint(n: int) -> bytes:
    """int64 varint: negatives encode as 10-byte two's complement."""
    if n < 0:
        n += 1 << 64
    return encode_uvarint(n)


def decode_uvarint(buf: bytes, pos: int = 0) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if shift >= 63 and result >= 1 << 64:
                raise ValueError("varint overflow")
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def decode_varint(buf: bytes, pos: int = 0) -> tuple[int, int]:
    v, pos = decode_uvarint(buf, pos)
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos


_TAG_CACHE: dict[int, bytes] = {}


def tag(field: int, wire: int) -> bytes:
    key = field << 3 | wire
    t = _TAG_CACHE.get(key)
    if t is None:
        t = _TAG_CACHE[key] = encode_uvarint(key)
    return t


class Writer:
    """Append-only protobuf message writer with proto3 omission helpers."""

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    # raw appends -----------------------------------------------------------
    def raw(self, b: bytes) -> "Writer":
        self.buf += b
        return self

    # field writers (proto3: zero values omitted) ---------------------------
    def uvarint(self, field: int, v: int) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_VARINT)
            self.buf += encode_uvarint(v)
        return self

    def varint(self, field: int, v: int) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_VARINT)
            self.buf += encode_varint(v)
        return self

    def bool(self, field: int, v: bool) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_VARINT)
            self.buf.append(1)
        return self

    def sfixed64(self, field: int, v: int) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_FIXED64)
            self.buf += struct.pack("<q", v)
        return self

    def fixed64(self, field: int, v: int) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_FIXED64)
            self.buf += struct.pack("<Q", v)
        return self

    def double(self, field: int, v: float) -> "Writer":
        if v != 0.0:
            self.buf += tag(field, WIRE_FIXED64)
            self.buf += struct.pack("<d", v)
        return self

    def bytes(self, field: int, v: bytes) -> "Writer":
        if v:
            self.buf += tag(field, WIRE_BYTES)
            self.buf += encode_uvarint(len(v))
            self.buf += v
        return self

    def string(self, field: int, v: str) -> "Writer":
        return self.bytes(field, v.encode("utf-8"))

    def message(self, field: int, body: bytes, always: bool = False) -> "Writer":
        """Embedded message. `always=True` mirrors gogoproto nullable=false
        (emit even when empty); default proto3 omits empty/absent messages."""
        if body or always:
            self.buf += tag(field, WIRE_BYTES)
            self.buf += encode_uvarint(len(body))
            self.buf += body
        return self

    def packed_varints(self, field: int, vs) -> "Writer":
        if vs:
            body = b"".join(encode_varint(v) for v in vs)
            self.message(field, body)
        return self

    def out(self) -> bytes:
        return bytes(self.buf)


def delimited(msg: bytes) -> bytes:
    """Varint length-prefixed framing (reference: libs/protoio — used for
    sign-bytes and all p2p/WAL message framing)."""
    return encode_uvarint(len(msg)) + msg


def parse_delimited(buf: bytes, pos: int = 0) -> tuple[bytes, int]:
    n, pos = decode_uvarint(buf, pos)
    if pos + n > len(buf):
        raise ValueError("truncated delimited message")
    return bytes(buf[pos : pos + n]), pos + n


class Reader:
    """Streaming field reader: yields (field_number, wire_type, value).

    value is int for varint/fixed, bytes for length-delimited.
    """

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: int | None = None) -> None:
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def __iter__(self):
        return self

    def __next__(self):
        if self.pos >= self.end:
            raise StopIteration
        key, self.pos = decode_uvarint(self.buf, self.pos)
        field, wire = key >> 3, key & 7
        if wire == WIRE_VARINT:
            v, self.pos = decode_uvarint(self.buf, self.pos)
        elif wire == WIRE_FIXED64:
            (v,) = struct.unpack_from("<Q", self.buf, self.pos)
            self.pos += 8
        elif wire == WIRE_BYTES:
            n, self.pos = decode_uvarint(self.buf, self.pos)
            if self.pos + n > self.end:
                raise ValueError("truncated bytes field")
            v = bytes(self.buf[self.pos : self.pos + n])
            self.pos += n
        elif wire == WIRE_FIXED32:
            (v,) = struct.unpack_from("<I", self.buf, self.pos)
            self.pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        return field, wire, v


def fields(buf: bytes) -> dict[int, list]:
    """Parse all fields into {field_number: [values...]}."""
    out: dict[int, list] = {}
    for field, _wire, v in Reader(buf):
        out.setdefault(field, []).append(v)
    return out


def as_sint64(v: int) -> int:
    """Reinterpret a decoded uvarint as int64."""
    return v - (1 << 64) if v >= 1 << 63 else v


def as_sfixed64(v: int) -> int:
    return v - (1 << 64) if v >= 1 << 63 else v
