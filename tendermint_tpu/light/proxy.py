"""Light proxy: an RPC server that serves VERIFIED chain data (reference:
light/proxy/proxy.go + routes.go).

Every response is checked against the light client's trust chain before it
leaves the proxy: commits/validators come from verified light blocks; raw
blocks fetched from the primary are accepted only when their hash matches
the verified header. A wallet pointed at the proxy gets full-node APIs with
light-client security.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tendermint_tpu.light.provider import json_rpc_call
from tendermint_tpu.types.ttime import Time


class LightProxy:
    """reference: light/proxy/proxy.go:24 Proxy."""

    def __init__(self, client, primary_rpc: str, laddr: str = "tcp://127.0.0.1:0"):
        self.client = client
        self.primary_rpc = primary_rpc.rstrip("/")
        host, port = laddr.split("://", 1)[-1].rsplit(":", 1)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, doc):
                body = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    result = proxy._dispatch(req.get("method", ""),
                                             req.get("params", {}) or {})
                    doc = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
                except Exception as e:  # noqa: BLE001
                    doc = {"jsonrpc": "2.0", "id": None,
                           "error": {"code": -32603, "message": str(e)}}
                self._respond(doc)

            def do_GET(self):
                # URI form like the node RPC: GET /status, /block?height=3
                # (rpc/server.py serves the same shape)
                try:
                    parsed = urllib.parse.urlparse(self.path)
                    method = parsed.path.strip("/")
                    params = {k: v[-1] for k, v in
                              urllib.parse.parse_qs(parsed.query).items()}
                    result = proxy._dispatch(method, params)
                    doc = {"jsonrpc": "2.0", "id": -1, "result": result}
                except Exception as e:  # noqa: BLE001
                    doc = {"jsonrpc": "2.0", "id": -1,
                           "error": {"code": -32603, "message": str(e)}}
                self._respond(doc)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.laddr = (f"tcp://{self._httpd.server_address[0]}"
                      f":{self._httpd.server_address[1]}")
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="light-proxy", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # --- verified routes (reference: light/proxy/routes.go) -----------------

    def _dispatch(self, method: str, params: dict):
        if method == "health":
            return {}
        if method == "status":
            lt = self.client.latest_trusted
            return {
                "sync_info": {
                    "latest_block_height": str(lt.height if lt else 0),
                    "latest_block_hash": (lt.hash().hex().upper() if lt else ""),
                    "catching_up": False,
                },
                "node_info": {"network": self.client.chain_id,
                              "moniker": "light-proxy"},
            }
        if method == "light_block":
            lb = self._verified(params)
            return {"height": str(lb.height), "light_block": lb.marshal().hex()}
        if method == "commit":
            lb = self._verified(params)
            return {"signed_header": {
                "header_hash": lb.hash().hex().upper(),
                "height": str(lb.height),
                "commit_round": lb.signed_header.commit.round,
                "signatures": len(lb.signed_header.commit.signatures),
            }, "canonical": True, "verified": True,
                "signed_header_proto": lb.signed_header.marshal().hex()}
        if method == "validators":
            lb = self._verified(params)
            return {
                "block_height": str(lb.height),
                "validator_set": lb.validator_set.marshal().hex(),
                "total": str(lb.validator_set.size()),
                "verified": True,
            }
        if method == "block":
            # Raw block from the primary, accepted only when its CONTENT
            # matches the verified header: every hash anchor in the returned
            # header JSON must equal the verified header's, and the tx list
            # must merkle-hash to the verified data_hash. The primary's own
            # block_id claim is never trusted (reference: the proxy's rpc
            # verification wrappers make the same binding).
            lb = self._verified(params)
            upstream = self._forward("block", {"height": str(lb.height)})
            self._check_block_against_header(upstream, lb)
            upstream["verified"] = True
            return upstream
        # everything else passes through unverified-but-labeled
        out = self._forward(method, params)
        if isinstance(out, dict):
            out.setdefault("verified", False)
        return out

    def _verified(self, params: dict):
        height = int(params.get("height", 0) or 0)
        if height == 0:
            lb = self.client.update(Time.now())
            if lb is None:
                lb = self.client.latest_trusted
            return lb
        return self.client.verify_light_block_at_height(height, Time.now())

    def _check_block_against_header(self, upstream: dict, lb) -> None:
        """Bind the primary's JSON block to the VERIFIED header: compare all
        hash anchors field by field and recompute the tx merkle root."""
        vh = lb.signed_header.header
        jh = upstream.get("block", {}).get("header", {})

        def hx(b: bytes) -> str:
            return (b or b"").hex().upper()

        anchors = {
            "height": str(vh.height),
            "chain_id": vh.chain_id,
            "app_hash": hx(vh.app_hash),
            "data_hash": hx(vh.data_hash),
            "validators_hash": hx(vh.validators_hash),
            "next_validators_hash": hx(vh.next_validators_hash),
            "consensus_hash": hx(vh.consensus_hash),
            "last_results_hash": hx(vh.last_results_hash),
            "evidence_hash": hx(vh.evidence_hash),
            "last_commit_hash": hx(vh.last_commit_hash),
            "proposer_address": hx(vh.proposer_address),
        }
        for key, want in anchors.items():
            got = str(jh.get(key, ""))
            if got != want:
                raise ValueError(
                    f"primary block header field {key!r} = {got!r} does not "
                    f"match verified header {want!r}")
        from tendermint_tpu.types.tx import txs_hash

        txs = [base64.b64decode(t)
               for t in upstream.get("block", {}).get("data", {}).get("txs", [])]
        data_hash = txs_hash(txs)
        if hx(data_hash) != anchors["data_hash"]:
            raise ValueError(
                "primary block txs do not merkle-hash to the verified "
                f"data_hash ({hx(data_hash)} != {anchors['data_hash']})")

    def _forward(self, method: str, params: dict):
        return json_rpc_call(self.primary_rpc, method, params, timeout=10)
