"""Witness cross-checking / attack detection (reference: light/detector.go).

After the primary's header is verified, every witness is asked for its block
at the same height. A hash mismatch means either the primary or the witness is
lying; the divergent trace is examined, LightClientAttackEvidence is built and
reported to BOTH providers (the honest one forwards it to the chain for
slashing), and the lying witness is dropped.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

from tendermint_tpu.light.provider import (
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    ProviderError,
)
from tendermint_tpu.types.evidence import LightClientAttackEvidence
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.ttime import Time


class ErrNoWitnesses(Exception):
    """All witnesses are dead or removed — cross-checking is impossible
    (reference: light/errors.go:66)."""


class ErrConflictingHeaders(Exception):
    """A witness reported a different header (reference: light/errors.go:88)."""

    def __init__(self, block: LightBlock, witness_index: int, witness=None):
        self.block = block
        self.witness_index = witness_index
        # the provider object itself: removal is identity-based so that a
        # concurrent witness-list mutation cannot redirect the index onto an
        # innocent witness
        self.witness = witness
        super().__init__(
            f"header hash ({block.hash().hex()}) from witness {witness_index} "
            "does not match primary"
        )


def _client_lock(client):
    """The client's verification lock when it has one (detect_divergence may
    be driven directly by harnesses holding only a bare stub client)."""
    return getattr(client, "_mtx", None) or contextlib.nullcontext()


@dataclass
class Divergence:
    """One detected attack (divergent witness + evidence built against the
    provider whose chain is wrong)."""

    witness_index: int
    evidence_against_primary: LightClientAttackEvidence | None
    evidence_against_witness: LightClientAttackEvidence | None


def compare_first_header_with_witnesses(client, sh: SignedHeader) -> None:
    """At initialization the trust-anchor header must match on every witness
    (reference: light/detector.go:376 compareFirstHeaderWithWitnesses)."""
    if not client.witnesses:
        return
    bad = []
    for i, w in enumerate(client.witnesses):
        try:
            lb = w.light_block(sh.height)
        except (ErrHeightTooHigh, ErrLightBlockNotFound, ProviderError):
            continue
        if lb.hash() != sh.hash():
            raise ErrConflictingHeaders(lb, i)
        if w.chain_id() != client.chain_id:
            bad.append(i)
    for i in reversed(bad):
        client.remove_witness(i)


def detect_divergence(client, new_lb: LightBlock, now: Time) -> None:
    """Cross-examine the freshly verified block (reference:
    light/detector.go:48 detectDivergence).

    A client configured WITH witnesses must never silently continue once all
    of them are dead/removed (reference returns ErrNoWitnesses); a client
    explicitly configured with zero witnesses skips detection.

    Runs under the client's verification lock and works over a snapshot of
    the witness list: two threads driving detection through one shared
    Client serialize here, and removal is by provider identity, so a
    witness can be removed at most once and a Divergence recorded at most
    once per (witness, conflicting header)."""
    with _client_lock(client):
        _detect_divergence_locked(client, new_lb, now)


def _detect_divergence_locked(client, new_lb: LightBlock, now: Time) -> None:
    witnesses = list(client.witnesses)
    if not witnesses:
        if getattr(client, "had_witnesses", False):
            raise ErrNoWitnesses("no witnesses connected. falling back to primary alone")
        return
    sh = new_lb.signed_header
    conflicts: list[ErrConflictingHeaders] = []
    dead: list = []
    for i, w in enumerate(witnesses):
        try:
            lb = w.light_block(sh.height)
        except ErrHeightTooHigh:
            continue  # witness hasn't caught up yet — not evidence of lying
        except (ErrLightBlockNotFound, ProviderError):
            dead.append(w)
            continue
        if lb.hash() != sh.hash():
            conflicts.append(ErrConflictingHeaders(lb, i, witness=w))

    substantiated = [c for c in conflicts
                     if _handle_conflicting_headers(client, c, new_lb, now)]
    # optional observer (the gateway's provider scoreboard). Three removal
    # reasons: "dead" (unresponsive — demotion material), "divergent" (a
    # conflicting header the witness could NOT substantiate — it lied),
    # and "substantiated" (the witness PROVED its divergent chain: it is
    # the whistleblower, the primary's chain is in question — do not
    # punish it for telling the truth)
    hook = getattr(client, "on_witness_removed", None)
    if hook is not None:
        sub_ids = {id(c) for c in substantiated}
        for w in dead:
            hook(w, "dead")
        for c in conflicts:
            hook(c.witness,
                 "substantiated" if id(c) in sub_ids else "divergent")
    _remove_witnesses(client, dead + [c.witness for c in conflicts])
    if substantiated:
        # The reference errors out so the caller re-examines trust
        # (light/detector.go:95-113); surface the first substantiated
        # conflict. Witnesses that could NOT prove their divergent header
        # from the common ancestor were merely dropped above — a single
        # lying witness must not fail an otherwise-valid verification
        # (reference: light/detector.go:105-110).
        raise substantiated[0]


def _remove_witnesses(client, providers) -> None:
    """Remove each provider from the client's witness list at most once,
    by identity (a concurrently mutated list can shift indices; popping by
    stale index would evict an innocent witness)."""
    if hasattr(client, "remove_witnesses"):
        client.remove_witnesses(providers)
        return
    seen: set[int] = set()
    for w in providers:
        if id(w) in seen:
            continue
        seen.add(id(w))
        for i, cur in enumerate(client.witnesses):
            if cur is w:
                client.remove_witness(i)
                break


def _substantiate(client, witness, common: LightBlock, target: LightBlock,
                  now: Time) -> bool:
    """Can the witness prove its divergent header from the common trusted
    ancestor? Runs the client's skipping bisection against the WITNESS with
    save=False (nothing enters the trusted store); any verification or
    provider failure means unsubstantiated (reference:
    light/detector.go:120-160 examineConflictingHeaderAgainstTrace)."""
    try:
        client._verify_skipping(witness, common, target, now, save=False)
        return True
    except Exception:  # noqa: BLE001 - any failure = unsubstantiated
        return False


def _handle_conflicting_headers(client, conflict: ErrConflictingHeaders,
                                primary_block: LightBlock, now: Time) -> bool:
    """Build and report evidence for one divergence; returns True iff the
    witness substantiated its conflicting header (reference:
    light/detector.go:116 compareNewHeaderWithWitness +
    examineConflictingHeaderAgainstTrace)."""
    witness = conflict.witness
    if witness is None:
        witness = client.witnesses[conflict.witness_index]
    common = client.latest_trusted
    if common is None or common.height >= primary_block.height:
        common = client.trusted_store.light_block_before(primary_block.height)
    if common is None:
        return False

    witness_block = conflict.block
    if not _substantiate(client, witness, common, witness_block, now):
        # Faulty/lying witness that can't back its header: caller drops it
        # and verification continues (reference: detector.go:105-110).
        return False

    # Evidence against whichever chain diverges from the common ancestor:
    # report both directions; honest full nodes discard the invalid one
    # (reference: light/detector.go:135-176 gatherEvidence). Evidence
    # against one chain names the OTHER chain's block as the trusted
    # counterpart for byzantine-validator extraction.
    ev_against_witness = make_attack_evidence(
        common, witness_block, primary_block.signed_header)
    ev_against_primary = make_attack_evidence(
        common, primary_block, witness_block.signed_header)
    # record the substantiated divergence on the client so callers (and
    # the live-attack harness) can inspect/resubmit the evidence after the
    # ErrConflictingHeaders surfaces; deduped per (witness, conflicting
    # header) so re-detection never double-records
    if hasattr(client, "divergences"):
        key = (id(witness), witness_block.hash())
        keys = getattr(client, "_divergence_keys", None)
        if keys is None or key not in keys:
            client.divergences.append(Divergence(
                conflict.witness_index, ev_against_primary, ev_against_witness))
            if keys is not None:
                keys.add(key)
    for ev, target in ((ev_against_witness, client.primary),
                       (ev_against_primary, witness)):
        if ev is None:
            continue
        try:
            target.report_evidence(ev)
        except ProviderError:
            pass
    return True


def make_attack_evidence(
    common: LightBlock, conflicted: LightBlock, trusted_sh=None,
) -> LightClientAttackEvidence | None:
    """reference: light/detector.go:271 newLightClientAttackEvidence.

    When the trusted counterpart header (the OTHER chain's block at the
    conflicting height) is supplied, the provably-faulty validators are
    extracted up front (reference fills ByzantineValidators the same way);
    the receiving pool re-derives and cross-checks them
    (evidence/verify.go:239-267)."""
    if conflicted is None:
        return None
    ev = LightClientAttackEvidence(
        conflicting_block=conflicted,
        common_height=common.height,
        total_voting_power=common.validator_set.total_voting_power(),
        timestamp=common.signed_header.header.time,
    )
    if trusted_sh is not None:
        ev.byzantine_validators = ev.get_byzantine_validators(
            common.validator_set, trusted_sh)
    return ev


__all__ = [
    "ErrConflictingHeaders",
    "Divergence",
    "compare_first_header_with_witnesses",
    "detect_divergence",
    "make_attack_evidence",
]
