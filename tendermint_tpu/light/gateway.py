"""Light-client serving gateway: verified-or-refused answers at crowd scale.

One LightGateway fronts many concurrent light clients with three planes:

* **Verified-answer plane** — a bounded cache keyed by height, populated
  only from ``verify_light_block``-accepted results (quarantined or
  unverified data can never enter it).  Concurrent queries for the same
  height coalesce into one in-flight verification (single-flight), whose
  commit checks batch through the continuous verify service
  (crypto/verify_service.py) when ``TMTPU_VERIFY_SERVICE=1`` — N clients
  cost one skip-sequence, not N.  Tx-proof queries are served off the
  self-healing stores: a typed-corruption read refuses (never serves
  corrupt bytes) and leaves healing to the scrub/repair plane.
* **Provider resilience** — per-provider retry with jittered exponential
  backoff behind the canonical ``light.gateway.fetch`` fault site, hedged
  secondary requests when the primary exceeds the latency budget, and a
  provider scoreboard mirroring utils/peerscore.py decay/ban discipline:
  slow providers are demoted (deprioritized while their decayed score is
  hot), lying ones (a header failing validation, or contradicting a
  witness in a substantiated divergence) are evicted permanently.
  Witness rotation pulls spares in on ErrNoWitnesses.
* **Typed degradation** — when fresh verification is impossible the
  gateway serves a stale-but-verified block within the trusting period,
  else refuses with :class:`ErrGatewayDegraded`.  A wrong answer is never
  an option; the lightcrowd soak invariant (e2e/soak.py) asserts exactly
  that under churn, bitrot, and live lunatic attacks.

docs/LIGHT.md has the architecture, verdict table and cookbook.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict

from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.light.detector import ErrConflictingHeaders, ErrNoWitnesses
from tendermint_tpu.light.provider import (
    ErrBadLightBlock,
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    ErrNoResponse,
    Provider,
    ProviderError,
)
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.light.verifier import header_expired
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.light_block import LightBlock
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.utils import faults, trace
from tendermint_tpu.utils.faults import FaultError
from tendermint_tpu.utils.peerscore import (
    SANCTION_NONE,
    PeerScoreBoard,
    ScoreConfig,
)

# Serving verdicts: every successful answer names how it was produced.
VERDICT_FRESH = "fresh"          # verified on this request
VERDICT_CACHED = "cached"        # bounded verified-answer cache hit
VERDICT_COALESCED = "coalesced"  # rode another client's in-flight verification
VERDICT_STALE = "stale"          # previously verified, within trust period,
                                 # served because fresh verification failed

# Offense points against the gateway scoreboard (same shape as
# utils/peerscore.py OFFENSE_POINTS; the ScoreConfig below maps 50 ->
# demotion and 100 -> ban, so one lying offense evicts immediately while
# slowness has to accumulate faster than the halflife decays it).
GATEWAY_OFFENSE_POINTS: dict[str, float] = {
    "slow_response": 10.0,
    "no_response": 25.0,
    "bad_light_block": 100.0,
    "conflicting_header": 100.0,
}

# Offenses that prove dishonesty rather than slowness: permanent eviction.
LYING_OFFENSES = frozenset({"bad_light_block", "conflicting_header"})

FETCH_SITE = "light.gateway.fetch"


class ErrGatewayDegraded(Exception):
    """The gateway cannot produce a verified answer and refuses to guess."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(f"gateway degraded: {reason}")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class GatewayConfig:
    """Env-tunable knobs (documented in docs/CONFIG.md)."""

    def __init__(self):
        self.retries = _env_int("TMTPU_GATEWAY_RETRIES", 2)
        self.backoff_s = _env_float("TMTPU_GATEWAY_BACKOFF_S", 0.05)
        self.hedge_s = _env_float("TMTPU_GATEWAY_HEDGE_S", 0.25)
        self.cache_cap = _env_int("TMTPU_GATEWAY_CACHE", 1024)
        self.n_witnesses = _env_int("TMTPU_GATEWAY_WITNESSES", 2)


class ProviderScoreBoard:
    """Provider health ledger mirroring utils/peerscore.py discipline:
    decaying scores with a halflife, a demotion threshold (slow providers
    sink in the fetch order until the decay forgives them), a scored-ban
    threshold, and permanent eviction for provably lying providers."""

    def __init__(self, clock=time.monotonic):
        self._board = PeerScoreBoard(
            ScoreConfig(halflife_s=120.0, disconnect_score=50.0,
                        ban_score=100.0, ban_duration_s=60.0,
                        ban_max_duration_s=600.0),
            clock=clock,
        )
        self._mtx = threading.Lock()
        self._lying: set[str] = set()
        self.evictions = 0

    def record(self, name: str, offense: str) -> str:
        sanction = self._board.record(
            name, offense, GATEWAY_OFFENSE_POINTS.get(offense, 1.0))
        if offense in LYING_OFFENSES:
            with self._mtx:
                if name not in self._lying:
                    self._lying.add(name)
                    self.evictions += 1
            return "evict"
        return sanction if sanction != SANCTION_NONE else "none"

    def evicted(self, name: str) -> bool:
        with self._mtx:
            if name in self._lying:
                return True
        return self._board.is_banned(name)

    def demoted(self, name: str) -> bool:
        return self._board.score(name) >= self._board.config.disconnect_score

    def rank(self, name: str) -> tuple:
        """Sort key: evicted last (callers filter them anyway), demoted
        after healthy, then by decayed score ascending."""
        return (self.evicted(name), self.demoted(name), self._board.score(name))

    def describe(self) -> dict:
        d = self._board.describe()
        with self._mtx:
            d["evicted"] = sorted(self._lying)
            d["evictions"] = self.evictions
        return d


class _GatewayProvider(Provider):
    """Wraps a raw provider so every fetch the inner Client makes flows
    through the gateway's instrumented path (fault site, retry/backoff,
    hedging, scoring)."""

    def __init__(self, gateway: "LightGateway", name: str, inner: Provider):
        self.gateway = gateway
        self.name = name
        self.inner = inner

    def chain_id(self) -> str:
        return self.inner.chain_id()

    def light_block(self, height: int) -> LightBlock:
        return self.gateway._fetch(self, height)

    def report_evidence(self, ev) -> None:
        self.inner.report_evidence(ev)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<gateway provider {self.name}>"


class LightGateway:
    """A witness/provider gateway serving many concurrent light clients.

    ``providers`` is an ordered pool: the first becomes the inner client's
    primary, the next ``TMTPU_GATEWAY_WITNESSES`` its witnesses, the rest
    spares used for hedged secondaries and witness rotation.  ``node``
    (optional) attaches a local full node for tx-proof queries off its
    self-healing stores.  ``clock``/``sleep`` are injectable for tests.
    """

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        providers: list[Provider],
        trusted_store: DBStore,
        *,
        provider_names: list[str] | None = None,
        node=None,
        config: GatewayConfig | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
        seed: int = 0,
        logger=None,
    ):
        if not providers:
            raise ValueError("gateway needs at least one provider")
        self.chain_id = chain_id
        self.node = node
        self.config = config if config is not None else GatewayConfig()
        self.logger = logger
        self._clock = clock
        self._sleep = sleep
        self._trust_options = trust_options
        self._rng = random.Random(f"gateway:{seed}")
        self.scoreboard = ProviderScoreBoard(clock=clock)

        names = provider_names or [f"p{i}" for i in range(len(providers))]
        if len(names) != len(providers):
            raise ValueError("provider_names must match providers")
        self._pool = [_GatewayProvider(self, n, p)
                      for n, p in zip(names, providers)]
        self._spares: list[_GatewayProvider] = []
        self._store = trusted_store
        self.client: Client | None = None  # set by _build_client
        self.divergences: list = []
        self._stat = threading.Lock()
        self.rebuilds = 0
        self.rotations = 0

        # bounded verified-answer cache: height -> LightBlock, inserted
        # only from verify_light_block-accepted results
        self._cache: OrderedDict[int, LightBlock] = OrderedDict()
        self._cache_mtx = threading.Lock()
        # single-flight: height -> Event of the leading verification
        self._flight: dict[int, threading.Event] = {}
        self._flight_mtx = threading.Lock()

        self.queries = 0
        self.cache_hits = 0
        self.coalesced = 0
        self.stale_served = 0
        self.refused = 0
        self.hedges = 0
        self.retries = 0

        # last: building the client fetches + verifies the trust anchor
        # through the instrumented fetch plane above
        self._build_client()

    # --- provider pool -----------------------------------------------------

    def _build_client(self) -> None:
        old = self.client
        store = old.trusted_store if old is not None else self._store
        for _ in range(len(self._pool)):
            alive = [w for w in self._pool
                     if not self.scoreboard.evicted(w.name)]
            if not alive:
                break
            alive.sort(key=lambda w: self.scoreboard.rank(w.name))
            k = max(0, self.config.n_witnesses)
            primary, witnesses = alive[0], alive[1:1 + k]
            self._spares = alive[1 + k:]
            try:
                self.client = Client(
                    self.chain_id, self._trust_options, primary, witnesses,
                    store, logger=self.logger,
                )
            except ErrConflictingHeaders as e:
                # a witness contradicted the TRUST ANCHOR at construction:
                # that provider is lying about pinned history — evict it
                # and rebuild around the rest
                liar = getattr(e, "witness", None) or (
                    witnesses[e.witness_index]
                    if 0 <= e.witness_index < len(witnesses) else primary)
                self.scoreboard.record(liar.name, "conflicting_header")
                continue
            self.client.on_witness_removed = self._witness_removed
            if old is not None:
                self.divergences.extend(old.divergences)
                with self._stat:
                    self.rebuilds += 1
            return
        raise ErrGatewayDegraded("every provider is evicted")

    def _witness_removed(self, wrapper, reason: str) -> None:
        """Detector hook (light/detector.py): witnesses the cross-check
        drops feed the scoreboard — an UNSUBSTANTIATED divergent header is
        lying (evict); a dead witness is demoted under the decay/ban
        discipline; a witness that SUBSTANTIATED its divergence is the
        whistleblower (the conflict handler deals with the primary) and
        takes no offense — the next rebuild re-seats it."""
        name = getattr(wrapper, "name", None)
        if name is None or reason == "substantiated":
            return
        self.scoreboard.record(
            name, "conflicting_header" if reason == "divergent"
            else "no_response")

    def _rotate_witnesses(self) -> bool:
        """On ErrNoWitnesses pull fresh non-evicted spares into the
        client's witness rotation; True iff any joined."""
        added = False
        while self._spares and len(self.client.witnesses) < self.config.n_witnesses:
            w = self._spares.pop(0)
            if self.scoreboard.evicted(w.name) or w is self.client.primary:
                continue
            self.client.add_witness(w)
            added = True
        if added:
            with self._stat:
                self.rotations += 1
        return added

    # --- fetch plane (retry/backoff/hedging/scoring) -----------------------

    def _fetch(self, wrapper: _GatewayProvider, height: int) -> LightBlock:
        spare = next(
            (s for s in self._spares
             if s is not wrapper and not self.scoreboard.evicted(s.name)),
            None)
        with trace.span(FETCH_SITE, provider=wrapper.name):
            if spare is None:
                return self._attempts(wrapper, height)
            return self._hedged(wrapper, spare, height)

    def _attempts(self, wrapper: _GatewayProvider, height: int,
                  score_slow: bool = True) -> LightBlock:
        """Per-provider retry loop with jittered exponential backoff."""
        cfg = self.config
        last: Exception | None = None
        for attempt in range(cfg.retries + 1):
            if attempt:
                with self._stat:
                    self.retries += 1
                self._sleep(cfg.backoff_s * (2 ** (attempt - 1))
                            * (0.5 + self._rng.random()))
            t0 = self._clock()
            try:
                faults.fire(FETCH_SITE)
                lb = wrapper.inner.light_block(height)
            except (ErrHeightTooHigh, ErrLightBlockNotFound):
                raise  # typed, deterministic answers: retrying cannot help
            except (ProviderError, FaultError, OSError) as e:
                self.scoreboard.record(wrapper.name, "no_response")
                last = e
                continue
            if score_slow and self._clock() - t0 > cfg.hedge_s:
                self.scoreboard.record(wrapper.name, "slow_response")
            try:
                lb.validate_basic(self.chain_id)
            except Exception as e:
                # malformed data is lying, not slowness: evict
                self.scoreboard.record(wrapper.name, "bad_light_block")
                raise ErrBadLightBlock(
                    f"provider {wrapper.name} returned an invalid light "
                    f"block: {e}") from e
            return lb
        raise last if last is not None else ErrNoResponse(
            f"provider {wrapper.name} kept failing")

    def _hedged(self, wrapper: _GatewayProvider, spare: _GatewayProvider,
                height: int) -> LightBlock:
        """Race the primary's retry sequence against a hedged secondary
        launched once the latency budget is exceeded."""
        state: dict = {"errs": [], "pending": 1}
        cond = threading.Condition()

        def run(w: _GatewayProvider, score_slow: bool) -> None:
            try:
                lb = self._attempts(w, height, score_slow=score_slow)
                err = None
            except Exception as e:  # noqa: BLE001 - collected and re-raised
                lb, err = None, e
            with cond:
                state["pending"] -= 1
                if lb is not None and "ok" not in state:
                    state["ok"] = lb
                if err is not None:
                    state["errs"].append(err)
                cond.notify_all()

        t = threading.Thread(target=run, args=(wrapper, False), daemon=True,
                             name=f"gw-fetch-{wrapper.name}")
        t.start()
        with cond:
            cond.wait_for(lambda: "ok" in state or state["pending"] == 0,
                          timeout=self.config.hedge_s)
            if "ok" in state:
                return state["ok"]
            if state["pending"] == 0:
                raise state["errs"][0]
            state["pending"] += 1
        # budget blown: primary is slow; fire the hedge
        trace.mark("light.gateway.hedge")
        with self._stat:
            self.hedges += 1
        self.scoreboard.record(wrapper.name, "slow_response")
        t2 = threading.Thread(target=run, args=(spare, True), daemon=True,
                              name=f"gw-hedge-{spare.name}")
        t2.start()
        with cond:
            cond.wait_for(lambda: "ok" in state or state["pending"] == 0)
            if "ok" in state:
                return state["ok"]
            raise state["errs"][0] if state["errs"] else ErrNoResponse(
                "hedged fetch failed")

    # --- verified-answer plane ---------------------------------------------

    def _cache_get(self, height: int) -> LightBlock | None:
        with self._cache_mtx:
            lb = self._cache.get(height)
            if lb is not None:
                self._cache.move_to_end(height)
            return lb

    def _cache_put(self, lb: LightBlock) -> None:
        with self._cache_mtx:
            self._cache[lb.height] = lb
            self._cache.move_to_end(lb.height)
            while len(self._cache) > max(1, self.config.cache_cap):
                self._cache.popitem(last=False)

    def serve_light_block(self, height: int,
                          now: Time | None = None) -> tuple[LightBlock, str]:
        """Serve a verified light block at ``height``; returns
        ``(light_block, verdict)`` or raises :class:`ErrGatewayDegraded`
        (or a typed provider error for unknown heights).  Never returns
        anything that did not pass light-client verification."""
        if now is None:
            now = Time.now()
        with trace.span("light.gateway.serve", height=height):
            with self._stat:
                self.queries += 1
            lb = self._cache_get(height)
            if lb is not None:
                with self._stat:
                    self.cache_hits += 1
                return lb, VERDICT_CACHED
            while True:
                with self._flight_mtx:
                    ev = self._flight.get(height)
                    if ev is None:
                        self._flight[height] = ev = threading.Event()
                        break
                ev.wait(timeout=60.0)
                lb = self._cache_get(height)
                if lb is not None:
                    with self._stat:
                        self.coalesced += 1
                    return lb, VERDICT_COALESCED
                # the leader failed; loop and try to lead ourselves
            try:
                lb = self._verify_height(height, now)
                self._cache_put(lb)
                return lb, VERDICT_FRESH
            except Exception as e:
                stale = self._stale_answer(height, now)
                if stale is not None:
                    with self._stat:
                        self.stale_served += 1
                    self._cache_put(stale)
                    return stale, VERDICT_STALE
                with self._stat:
                    self.refused += 1
                if isinstance(e, (ErrGatewayDegraded, ErrHeightTooHigh,
                                  ErrLightBlockNotFound)):
                    raise
                raise ErrGatewayDegraded(str(e)) from e
            finally:
                with self._flight_mtx:
                    self._flight.pop(height, None)
                ev.set()

    def serve_latest(self, now: Time | None = None) -> tuple[LightBlock, str]:
        """Serve the latest verified light block, refreshing from the
        providers first.  When no provider can produce a fresh verified
        head, degrade to the latest stale-but-verified block within the
        trusting period, else refuse with :class:`ErrGatewayDegraded`."""
        if now is None:
            now = Time.now()
        with trace.span("light.gateway.serve", height=0):
            with self._stat:
                self.queries += 1
            try:
                lb = self.client.update(now)
                if lb is None:
                    lb = self.client.latest_trusted
                self._cache_put(lb)
                return lb, VERDICT_FRESH
            except Exception as e:
                latest = self.client.latest_trusted
                if latest is not None and not header_expired(
                        latest.signed_header, self.client.trusting_period_s,
                        now):
                    with self._stat:
                        self.stale_served += 1
                    return latest, VERDICT_STALE
                with self._stat:
                    self.refused += 1
                raise ErrGatewayDegraded(
                    f"no fresh head and trusted state expired: {e}") from e

    def _stale_answer(self, height: int, now: Time) -> LightBlock | None:
        """A previously verified block at this height, iff still inside
        the trusting period (typed degradation: stale-but-verified)."""
        lb = self.client.trusted_store.light_block(height)
        if lb is None:
            return None
        if header_expired(lb.signed_header, self.client.trusting_period_s, now):
            return None
        return lb

    def _verify_height(self, height: int, now: Time) -> LightBlock:
        last: Exception | None = None
        for _ in range(2):
            try:
                return self.client.verify_light_block_at_height(height, now)
            except ErrConflictingHeaders as e:
                # A witness substantiated a divergent header: the primary
                # is contradicted by a provable chain. The detector already
                # built + reported the evidence both ways; evict the
                # primary, rebuild around the witness set, retry once.
                last = e
                self.scoreboard.record(self.client.primary.name,
                                       "conflicting_header")
                self._build_client()
            except ErrNoWitnesses as e:
                last = e
                if not self._rotate_witnesses():
                    raise
        raise last if last is not None else ErrGatewayDegraded(
            "verification kept failing")

    # --- tx-proof plane ------------------------------------------------------

    def serve_tx(self, tx_hash_bytes: bytes,
                 now: Time | None = None) -> dict:
        """Tx lookup + Merkle inclusion proof verified against the
        gateway-verified header at that height, off the attached node's
        self-healing stores.  A typed-corruption read refuses — corrupt
        bytes are never served; the scrub/repair plane heals the row."""
        if self.node is None:
            raise ErrGatewayDegraded("no local node attached for tx queries")
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is None:
            raise ErrGatewayDegraded("transaction indexing is disabled")
        from tendermint_tpu.types.tx import tx_hash, txs_proof

        try:
            res = indexer.get(tx_hash_bytes)
            if res is None:
                raise ErrLightBlockNotFound(
                    f"tx ({tx_hash_bytes.hex()}) not found")
            height, idx = int(res["height"]), int(res["index"])
            block = self.node.block_store.load_block(height)
        except CorruptedStoreError as e:
            with self._stat:
                self.refused += 1
            raise ErrGatewayDegraded(
                f"store row quarantined, refusing to serve: {e}") from e
        if block is None:
            with self._stat:
                self.refused += 1
            raise ErrGatewayDegraded(
                f"block at height {height} unavailable for proof")
        txs = list(block.data.txs)
        root, proof = txs_proof(txs, idx)
        lb, verdict = self.serve_light_block(height, now)
        if root != lb.signed_header.header.data_hash:
            # the local store disagrees with the verified chain: refuse
            with self._stat:
                self.refused += 1
            raise ErrGatewayDegraded(
                "tx proof root does not match the verified header")
        proof.verify(root, tx_hash(txs[idx]))
        return {
            "height": height,
            "index": idx,
            "tx": txs[idx],
            "root_hash": root,
            "proof": proof,
            "verdict": verdict,
        }

    # --- introspection -------------------------------------------------------

    def describe(self) -> dict:
        with self._stat:
            counters = {
                "queries": self.queries,
                "cache_hits": self.cache_hits,
                "coalesced": self.coalesced,
                "stale_served": self.stale_served,
                "refused": self.refused,
                "hedges": self.hedges,
                "retries": self.retries,
                "rebuilds": self.rebuilds,
                "rotations": self.rotations,
            }
        with self._cache_mtx:
            cache = {"size": len(self._cache),
                     "cap": self.config.cache_cap}
        latest = self.client.latest_trusted
        return {
            "chain_id": self.chain_id,
            "latest_trusted": latest.height if latest is not None else 0,
            "primary": self.client.primary.name,
            "witnesses": [w.name for w in self.client.witnesses],
            "spares": [s.name for s in self._spares],
            "counters": counters,
            "cache": cache,
            "providers": self.scoreboard.describe(),
            "divergences": len(self.divergences) + len(self.client.divergences),
        }

    def all_divergences(self) -> list:
        return list(self.divergences) + list(self.client.divergences)


__all__ = [
    "ErrGatewayDegraded",
    "GatewayConfig",
    "GATEWAY_OFFENSE_POINTS",
    "LightGateway",
    "ProviderScoreBoard",
    "VERDICT_CACHED",
    "VERDICT_COALESCED",
    "VERDICT_FRESH",
    "VERDICT_STALE",
]
