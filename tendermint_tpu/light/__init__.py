"""Light client (reference: light/).

 - verifier: pure header verification (adjacent / non-adjacent / backwards)
 - client: Client with sequential + skipping (bisection) modes, trust anchor
   options, trusted-store persistence, witness cross-checking
 - detector: divergence detection + LightClientAttackEvidence construction
 - provider: Mock / local-node / JSON-RPC light-block providers
 - store: DB-backed trusted store
 - range_verify: whole-chain sequential verification in ONE BatchVerifier
   flush (BASELINE config 3: 10k headers -> one TPU kernel launch)
 - gateway: LightGateway serving many concurrent clients (verified-answer
   cache, provider failover/hedging/scoreboard, typed degradation)
"""

from tendermint_tpu.light.client import SEQUENTIAL, SKIPPING, Client, TrustOptions
from tendermint_tpu.light.gateway import ErrGatewayDegraded, LightGateway
from tendermint_tpu.light.provider import (
    HTTPProvider,
    MockProvider,
    NodeProvider,
    Provider,
)
from tendermint_tpu.light.range_verify import verify_header_range
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    LightClientError,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "Client",
    "TrustOptions",
    "LightGateway",
    "ErrGatewayDegraded",
    "SEQUENTIAL",
    "SKIPPING",
    "Provider",
    "MockProvider",
    "NodeProvider",
    "HTTPProvider",
    "DBStore",
    "verify",
    "verify_adjacent",
    "verify_non_adjacent",
    "verify_backwards",
    "verify_header_range",
    "DEFAULT_TRUST_LEVEL",
    "LightClientError",
]
