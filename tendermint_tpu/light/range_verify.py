"""Batched sequential header-range verification — BASELINE config 3.

The reference light client verifies a header chain one header at a time, each
`VerifyAdjacent` paying a serial loop of ed25519 verifies
(light/verifier.go:93 -> types/validator_set.go:719). On TPU that is the wrong
shape: a 10k-header catch-up is ~10k * 2/3|V| signatures that are all known up
front.

`verify_header_range` does the cheap hash-linkage checks serially on host
(NextValidatorsHash chaining, time monotonicity, validator-hash match), queues
every commit's serial-semantics signature prefix into ONE BatchVerifier flush
(one wide TPU kernel launch), then replays each header's serial accept/reject
decision over the returned bitmap. The overall accept/reject matches running
verify_adjacent per header; the one reporting difference is error ORDERING:
a structural defect anywhere in the range is detected in the host pass and
therefore reported before a bad SIGNATURE at an earlier height (a sequential
loop would hit the earlier signature first) -- and the set-size check
(len(signatures) == validator set size) runs even earlier, in the dispatch
phase, so a set-size mismatch at a LATER height is reported before any
structural or signature error at an earlier one. Chains that a sequential
loop accepts are accepted with identical side effects.
"""

from __future__ import annotations

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.light import verifier as lv
from tendermint_tpu.types.light_block import LightBlock
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
)


class RangeVerifyError(lv.LightClientError):
    def __init__(self, height: int, reason: Exception | str):
        self.height = height
        self.reason = reason
        super().__init__(f"header range verification failed at height {height}: {reason}")


def verify_header_range(trusted: LightBlock, chain: list[LightBlock],
                        trusting_period_s: float, now: Time,
                        max_clock_drift_s: float = 10.0,
                        store=None) -> None:
    """Verify `chain` (ascending, adjacent heights) against `trusted`.

    Raises RangeVerifyError naming the failing height (see module docstring
    for the error-ordering caveat vs a sequential loop). When `store` is
    given, every verified block is saved into it.
    """
    if not chain:
        return
    # Hash every header in the range as one batched merkle forest before
    # the serial replay walks them (types/block.py precompute_header_hashes).
    from tendermint_tpu.types.block import precompute_header_hashes

    precompute_header_hashes(
        [lb.signed_header.header for lb in chain
         if lb.signed_header and lb.signed_header.header])
    # Phase 1 (DISPATCH): collect signature items and dispatch them in
    # chunks as early as possible -- the tunnel's ~90 ms round trip is pure
    # latency, so results dispatched now travel home (copy_to_host_async in
    # ops dispatch) while phase 2 validates structure on host.  EVERY chunk,
    # including the sub-crossover tail, is dispatched with
    # force_device=use_device, so once the range is device-sized the tail
    # flies with the other chunks instead of burning synchronous host CPU.
    from tendermint_tpu.ops import ed25519_batch as _edb

    # Split into EVEN device chunks of ~2,500 signatures (measured sweet
    # spot: smaller chunks dispatch earlier and overlap more of the tunnel
    # flight; much smaller ones just multiply per-dispatch host overhead).
    # Chunks are FORCED onto the device path — a sub-crossover chunk would
    # otherwise run on host CPU synchronously (15 us/sig of 1-core time
    # that overlaps nothing) while a device flight is free. Ranges whose
    # whole signature count sits below the crossover stay one host flush.
    # Each chunk dispatch lands on the continuous-batching verify service
    # (crypto/verify_service.py): chunks queued within its coalescing
    # window share ONE kernel launch (and its sync floor) with each other
    # and with any concurrent drain/fast-sync traffic, which also removes
    # the per-chunk launch jitter behind the r05 spread (ISSUE 11
    # satellite 1) — the executor, not this caller, owns launch cadence
    # and the single batched readback.
    crossover = _edb.host_crossover()
    est_per = max(1, (2 * chain[0].validator_set.size()) // 3 + 1)
    est_total = est_per * len(chain)
    use_device = est_total > crossover
    k = max(1, round(est_total / 2500)) if use_device else 1
    chunk_sigs_target = (-(-est_total // k)) if k > 1 else est_total + 1
    verifier = crypto_batch.create_batch_verifier()
    plan = []  # (lb, prefix, needed)
    pending = []  # (plan_chunk, PendingVerify)
    for lb in chain:
        sh, vals = lb.signed_header, lb.validator_set
        commit = sh.commit
        if vals.size() != len(commit.signatures):
            # full structural pass runs in phase 2; this one gates the
            # prefix computation itself
            raise RangeVerifyError(
                sh.height, f"wrong set size: {vals.size()} vs {len(commit.signatures)}")
        needed = vals.total_voting_power() * 2 // 3
        prefix = vals.commit_light_prefix(commit, needed)
        chain_id = sh.header.chain_id
        validators = vals.validators
        signatures = commit.signatures
        add = verifier.add
        for idx in prefix:
            add(validators[idx].pub_key, commit.vote_sign_bytes(chain_id, idx),
                signatures[idx].signature)
        plan.append((lb, prefix, needed))
        if len(verifier) >= chunk_sigs_target:
            pending.append((plan, verifier.dispatch(force_device=use_device)))
            verifier = crypto_batch.create_batch_verifier()
            plan = []
    if plan:
        pending.append((plan, verifier.dispatch(force_device=use_device)))

    # Phase 2 (STRUCTURE, overlapping the signature flights): the serial
    # chain-linkage walk.  Same accept/reject set as the sequential loop;
    # the module docstring's error-ordering caveat (structural defects
    # reported before an earlier height's bad signature) already covers
    # this ordering.
    prev = trusted
    for lb in chain:
        sh, vals = lb.signed_header, lb.validator_set
        if sh.height != prev.height + 1:
            raise RangeVerifyError(sh.height, "headers must be adjacent in height")
        if lv.header_expired(prev.signed_header, trusting_period_s, now):
            raise RangeVerifyError(
                sh.height, lv.ErrOldHeaderExpired(
                    Time.from_unix_ns(prev.signed_header.header.time.unix_ns()
                                      + int(trusting_period_s * 1e9)), now))
        try:
            lv._verify_new_header_and_vals(
                sh, vals, prev.signed_header, now, max_clock_drift_s)
        except lv.LightClientError as e:
            raise RangeVerifyError(sh.height, e) from e
        if sh.header.validators_hash != prev.signed_header.header.next_validators_hash:
            raise RangeVerifyError(
                sh.height,
                f"expected old header next validators "
                f"({prev.signed_header.header.next_validators_hash.hex()}) to match "
                f"those from new header ({sh.header.validators_hash.hex()})"
            )
        prev = lb

    # Phase 3: ONE readback for every chunk's flush (crypto_batch.prefetch
    # batches every pending's device outputs into one device_get; most
    # results have already landed).
    crypto_batch.prefetch([pv for (_, pv) in pending])

    # Phase 4: replay each header's serial decision over its bitmap slice.
    for plan_chunk, pv in pending:
        _, bitmap = pv.resolve()
        pos = 0
        for lb, prefix, needed in plan_chunk:
            vals, commit = lb.validator_set, lb.signed_header.commit
            tallied = 0
            ok_height = False
            for idx, ok in zip(prefix, bitmap[pos:pos + len(prefix)]):
                if not ok:
                    raise RangeVerifyError(
                        lb.height,
                        ErrWrongSignature(idx, commit.signatures[idx].signature))
                tallied += vals.validators[idx].voting_power
                if tallied > needed:
                    ok_height = True
                    break
            pos += len(prefix)
            if not ok_height:
                raise RangeVerifyError(
                    lb.height, ErrNotEnoughVotingPowerSigned(tallied, needed))
            if store is not None:
                store.save_light_block(lb)
