"""Light client (reference: light/client.go).

Verifies headers from a primary provider against a trust anchor, using
sequential or skipping (bisection) verification, cross-checks every newly
verified header against witness providers (detector.py), and persists
verified blocks in a trusted store.

TPU angle: every commit check inside verify funnels through the batched
BatchVerifier (types/validator_set.py), so one bisection step costs at most
two kernel flushes; verify_header_range (range_verify.py) does whole-chain
sequential verification in a single flush.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.light import verifier as lv
from tendermint_tpu.light.detector import (
    compare_first_header_with_witnesses,
    detect_divergence,
)
from tendermint_tpu.light.provider import (
    ErrLightBlockNotFound,
    Provider,
    ProviderError,
)
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrOldHeaderExpired,
    LightClientError,
    validate_trust_level,
)
from tendermint_tpu.types.light_block import LightBlock
from tendermint_tpu.types.ttime import Time

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT_S = 10.0
DEFAULT_MAX_RETRY_ATTEMPTS = 10


@dataclass
class TrustOptions:
    """Trust anchor (reference: light/client.go:58-84 TrustOptions)."""

    period_s: float
    height: int
    hash: bytes

    def validate_basic(self) -> None:
        if self.period_s <= 0:
            raise LightClientError("negative or zero trusting period")
        if self.height <= 0:
            raise LightClientError("negative or zero height")
        if len(self.hash) != 32:
            raise LightClientError(
                f"expected hash size to be 32 bytes, got {len(self.hash)} bytes"
            )


from tendermint_tpu.light.detector import ErrNoWitnesses  # noqa: E402  (re-export)


class Client:
    """reference: light/client.go:174 (Client struct), :225 NewClient."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider],
        trusted_store: DBStore,
        *,
        verification_mode: str = SKIPPING,
        trust_level: tuple[int, int] = DEFAULT_TRUST_LEVEL,
        max_clock_drift_s: float = DEFAULT_MAX_CLOCK_DRIFT_S,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        logger=None,
    ):
        import threading

        # one lock around all public verification entry points (the Go
        # reference holds c.mtx); providers/stores are not thread-safe
        self._mtx = threading.RLock()
        if verification_mode not in (SEQUENTIAL, SKIPPING):
            raise LightClientError(f"unknown verification mode {verification_mode}")
        validate_trust_level(trust_level)
        trust_options.validate_basic()
        self.chain_id = chain_id
        self.trusting_period_s = trust_options.period_s
        self.verification_mode = verification_mode
        self.trust_level = trust_level
        self.max_clock_drift_s = max_clock_drift_s
        self.primary = primary
        self.witnesses = list(witnesses)
        self.had_witnesses = bool(witnesses)
        self.trusted_store = trusted_store
        self.pruning_size = pruning_size
        self.logger = logger
        # substantiated attacks the detector proved (light/detector.py
        # Divergence records): the live-attack harness reads the built
        # evidence from here after ErrConflictingHeaders surfaces
        self.divergences: list = []
        # dedup keys for Divergence records: (witness identity, header hash)
        self._divergence_keys: set = set()
        self.latest_trusted: LightBlock | None = trusted_store.latest_light_block()
        if self.latest_trusted is None:
            self._initialize(trust_options)
        else:
            self._check_trusted_header_using_options(trust_options)

    # --- initialization (reference: light/client.go:352-431) ---------------

    def _initialize(self, opts: TrustOptions) -> None:
        lb = self._light_block_from_primary(opts.height)
        # Ensure the header matches the trusted hash, then self-verify:
        # 2/3 of the block's OWN validator set must have signed
        # (reference: light/client.go:381-418).
        if lb.hash() != opts.hash:
            raise LightClientError(
                f"expected header's hash {opts.hash.hex()}, but got {lb.hash().hex()}"
            )
        lb.validate_basic(self.chain_id)
        lb.validator_set.verify_commit_light(
            self.chain_id,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
        )
        compare_first_header_with_witnesses(self, lb.signed_header)
        self._update_trusted_light_block(lb)

    def _check_trusted_header_using_options(self, opts: TrustOptions) -> None:
        """Existing trusted state vs new options (reference:
        light/client.go:272-350 checkTrustedHeaderUsingOptions)."""
        primary_hash = None
        if self.latest_trusted.height >= opts.height:
            stored = self.trusted_store.light_block(opts.height)
            if stored is not None:
                primary_hash = stored.hash()
        if primary_hash is None:
            lb = self._light_block_from_primary(opts.height)
            primary_hash = lb.hash()
        if primary_hash != opts.hash:
            # Trust anchor changed: wipe and restart from options.
            self._cleanup()
            self._initialize(opts)

    # --- public API --------------------------------------------------------

    def trusted_light_block(self, height: int) -> LightBlock:
        """reference: light/client.go:1011 TrustedLightBlock."""
        latest = self.latest_trusted
        if latest is None:
            raise LightClientError("no trusted state yet")
        if height > latest.height:
            raise LightClientError(
                f"height requested is too high: {height} vs latest {latest.height}"
            )
        lb = self.trusted_store.light_block(height)
        if lb is None:
            raise LightClientError(f"no light block at height {height}")
        return lb

    def first_trusted_height(self) -> int:
        return self.trusted_store.first_light_block_height()

    def update(self, now: Time) -> LightBlock | None:
        """Verify the latest header from primary if newer than latest trusted
        (reference: light/client.go:443 Update)."""
        with self._mtx:
            latest_trusted = self.latest_trusted
            if latest_trusted is None:
                raise LightClientError("no trusted state yet")
            latest = self._light_block_from_primary(0)
            if latest.height > latest_trusted.height:
                self.verify_light_block(latest, now)
                return latest
            return None

    def verify_light_block_at_height(self, height: int, now: Time) -> LightBlock:
        """reference: light/client.go:474 VerifyLightBlockAtHeight."""
        with self._mtx:
            if height <= 0:
                raise LightClientError("negative or zero height")
            lb = self.trusted_store.light_block(height)
            if lb is not None:
                return lb
            lb = self._light_block_from_primary(height)
            self.verify_light_block(lb, now)
            return lb

    def verify_light_block(self, new_lb: LightBlock, now: Time) -> None:
        """reference: light/client.go:525 VerifyHeader (+ :558
        verifyLightBlock)."""
        with self._mtx:
            self._verify_light_block_locked(new_lb, now)

    def _verify_light_block_locked(self, new_lb: LightBlock, now: Time) -> None:
        h = self.trusted_store.light_block(new_lb.height)
        if h is not None:
            if h.hash() == new_lb.hash():
                return
            raise LightClientError(
                f"existing trusted header {h.hash().hex()} does not match "
                f"new header {new_lb.hash().hex()}"
            )
        new_lb.validate_basic(self.chain_id)

        latest = self.latest_trusted
        if latest is not None and new_lb.height < latest.height:
            # Historical header: find closest trusted below, verify forward,
            # or walk backwards from the first trusted block
            # (reference: light/client.go:558-600 verifyLightBlock).
            closest = self.trusted_store.light_block_before(new_lb.height)
            if closest is not None:
                self._verify_from(closest, new_lb, now)
            else:
                first = self.trusted_store.light_block(self.first_trusted_height())
                self._backwards(first, new_lb)
        else:
            anchor = latest
            if anchor is None:
                raise LightClientError("no trusted state yet")
            self._verify_from(anchor, new_lb, now)

        detect_divergence(self, new_lb, now)
        self._update_trusted_light_block(new_lb)

    # --- verification strategies ------------------------------------------

    def _verify_from(self, trusted: LightBlock, new_lb: LightBlock, now: Time) -> None:
        if self.verification_mode == SEQUENTIAL:
            self._verify_sequential(trusted, new_lb, now)
        else:
            self._verify_skipping_against_primary(trusted, new_lb, now)

    def _verify_sequential(self, trusted: LightBlock, new_lb: LightBlock, now: Time) -> None:
        """Verify every header in (trusted, new] (reference:
        light/client.go:613 verifySequential)."""
        verified = trusted
        for height in range(trusted.height + 1, new_lb.height + 1):
            inter = new_lb if height == new_lb.height else self._light_block_from_primary(height)
            lv.verify_adjacent(
                verified.signed_header,
                inter.signed_header,
                inter.validator_set,
                self.trusting_period_s,
                now,
                self.max_clock_drift_s,
            )
            if height != new_lb.height:
                self.trusted_store.save_light_block(inter)
            verified = inter

    def _verify_skipping_against_primary(
        self, trusted: LightBlock, new_lb: LightBlock, now: Time
    ) -> None:
        self._verify_skipping(self.primary, trusted, new_lb, now)

    def _verify_skipping(
        self, source: Provider, trusted: LightBlock, new_lb: LightBlock,
        now: Time, save: bool = True
    ) -> list[LightBlock]:
        """Bisection (reference: light/client.go:706 verifySkipping).

        Maintains a stack of pending blocks; on ErrNewValSetCantBeTrusted,
        fetch the midpoint and retry against it. With save=False nothing is
        written to the trusted store (the detector substantiates a witness's
        divergent header without polluting trust).
        """
        block_cache = [new_lb]
        verified_blocks = []
        depth = 0
        verified = trusted
        # Captured once: self.primary may be reassigned mid-bisection by a
        # witness promotion inside _light_block_from_primary.
        use_primary = source is self.primary
        while True:
            candidate = block_cache[depth]
            try:
                lv.verify(
                    verified.signed_header,
                    verified.validator_set,
                    candidate.signed_header,
                    candidate.validator_set,
                    self.trusting_period_s,
                    now,
                    self.max_clock_drift_s,
                    self.trust_level,
                )
            except lv.ErrNewValSetCantBeTrusted:
                # Can't skip that far: bisect (reference client.go:755-776).
                pivot = (verified.height + candidate.height) // 2
                if pivot == verified.height:
                    raise LightClientError(
                        "bisection failed to converge "
                        f"({verified.height} -> {candidate.height})"
                    )
                inter = (
                    self._light_block_from_primary(pivot)
                    if use_primary
                    else source.light_block(pivot)
                )
                inter.validate_basic(self.chain_id)
                block_cache.insert(depth + 1, inter)
                depth += 1
                continue
            # Verified one step.
            if candidate.height == new_lb.height:
                return verified_blocks
            verified = candidate
            verified_blocks.append(candidate)
            if save and candidate.height != new_lb.height:
                self.trusted_store.save_light_block(candidate)
            depth = 0
            block_cache = [b for b in block_cache if b.height > candidate.height]
            if not block_cache:
                block_cache = [new_lb]

    def _backwards(self, trusted: LightBlock, new_lb: LightBlock) -> None:
        """Hash-linked walk below the first trusted header (reference:
        light/client.go:942 backwards)."""
        verified = trusted.signed_header.header
        for height in range(trusted.height - 1, new_lb.height - 1, -1):
            inter = (
                new_lb
                if height == new_lb.height
                else self._light_block_from_primary(height)
            )
            lv.verify_backwards(inter.signed_header.header, verified)
            verified = inter.signed_header.header

    # --- maintenance -------------------------------------------------------

    def _update_trusted_light_block(self, lb: LightBlock) -> None:
        self.trusted_store.save_light_block(lb)
        if self.pruning_size > 0:
            self.trusted_store.prune(self.pruning_size)
        if self.latest_trusted is None or lb.height > self.latest_trusted.height:
            self.latest_trusted = lb

    def _cleanup(self) -> None:
        """Remove all trusted state (reference: light/client.go:1041)."""
        hs = []
        h = self.trusted_store.first_light_block_height()
        latest = self.trusted_store.latest_light_block()
        if h > 0 and latest is not None:
            hs = range(h, latest.height + 1)
        for height in hs:
            self.trusted_store.delete_light_block(height)
        self.latest_trusted = None

    def _light_block_from_primary(self, height: int) -> LightBlock:
        """Fetch from primary; on failure, promote a witness (reference:
        light/client.go:1080 lightBlockFromPrimary + replacePrimaryProvider)."""
        try:
            lb = self.primary.light_block(height)
            lb.validate_basic(self.chain_id)
            return lb
        except (ProviderError, ValueError) as primary_err:
            if isinstance(primary_err, ErrLightBlockNotFound):
                raise
            # Replace primary with the first responsive witness.
            for i, w in enumerate(self.witnesses):
                try:
                    lb = w.light_block(height)
                    lb.validate_basic(self.chain_id)
                except (ProviderError, ValueError):
                    continue
                self.primary = w
                self.witnesses = self.witnesses[:i] + self.witnesses[i + 1:]
                return lb
            raise

    def remove_witness(self, idx: int) -> None:
        """Drop the witness at idx; tolerant of a concurrent removal having
        already shrunk the list (locked — indices are only meaningful under
        the verification lock)."""
        with self._mtx:
            if 0 <= idx < len(self.witnesses):
                self.witnesses.pop(idx)

    def remove_witnesses(self, providers) -> None:
        """Identity-based removal: each provider leaves the witness list at
        most once, regardless of how indices shifted since the caller
        observed them."""
        with self._mtx:
            seen: set[int] = set()
            for w in providers:
                if id(w) in seen:
                    continue
                seen.add(id(w))
                for i, cur in enumerate(self.witnesses):
                    if cur is w:
                        self.witnesses.pop(i)
                        break

    def add_witness(self, provider: Provider) -> None:
        """Rotate a fresh witness in (gateway witness rotation on
        ErrNoWitnesses)."""
        with self._mtx:
            if provider is not self.primary and \
                    all(w is not provider for w in self.witnesses):
                self.witnesses.append(provider)
                self.had_witnesses = True
