"""Light-block providers (reference: light/provider/provider.go,
light/provider/http/http.go, light/provider/mock/mock.go).

A Provider serves LightBlocks for a chain and accepts evidence reports.
Three implementations:

 - MockProvider: canned header map (the reference's light/provider/mock),
   used by tests and the detector tests.
 - NodeProvider: reads straight from a local BlockStore+StateStore pair —
   the in-process analogue of pointing the light client at a full node,
   also used by the state-sync state provider.
 - HTTPProvider: JSON-RPC client against a node's RPC server (reference:
   light/provider/http/http.go:65 LightBlock = SignedHeader via /commit +
   ValidatorSet via /validators).
"""

from __future__ import annotations

import abc
import json
import urllib.request

from tendermint_tpu.types.light_block import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class ErrHeightTooHigh(ProviderError):
    """The height is higher than the provider's last block (reference:
    light/provider/errors.go:12)."""


class ErrLightBlockNotFound(ProviderError):
    """Provider can't find the requested light block (reference:
    light/provider/errors.go:16)."""


class ErrNoResponse(ProviderError):
    """Provider doesn't respond (reference: light/provider/errors.go:20)."""


class ErrBadLightBlock(ProviderError):
    """Provider returned an invalid light block (reference:
    light/provider/errors.go:24)."""


class Provider(abc.ABC):
    @abc.abstractmethod
    def chain_id(self) -> str: ...

    @abc.abstractmethod
    def light_block(self, height: int) -> LightBlock:
        """LightBlock at the given height; height=0 means latest. Raises a
        ProviderError subclass on failure (reference:
        light/provider/provider.go:14-26)."""

    @abc.abstractmethod
    def report_evidence(self, ev) -> None: ...


class MockProvider(Provider):
    """Canned light blocks keyed by height (reference: light/provider/mock)."""

    def __init__(self, chain_id: str, light_blocks: dict[int, LightBlock]):
        self._chain_id = chain_id
        self._lbs = dict(light_blocks)
        self.evidences: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if not self._lbs:
            raise ErrNoResponse("mock provider is empty")
        if height == 0:
            height = max(self._lbs)
        if height > max(self._lbs):
            raise ErrHeightTooHigh(f"no block at height {height}")
        lb = self._lbs.get(height)
        if lb is None:
            raise ErrLightBlockNotFound(f"no block at height {height}")
        return lb

    def add(self, lb: LightBlock) -> None:
        self._lbs[lb.height] = lb

    def remove(self, height: int) -> None:
        self._lbs.pop(height, None)

    def report_evidence(self, ev) -> None:
        self.evidences.append(ev)


class NodeProvider(Provider):
    """Serves light blocks from a local node's stores — the trusted-source
    analogue of an RPC provider without the wire hop."""

    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self._block_store = block_store
        self._state_store = state_store
        self.evidences: list = []

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        tip = self._block_store.height
        if height == 0:
            height = tip
        if height > tip:
            raise ErrHeightTooHigh(f"no block at height {height}")
        from tendermint_tpu.store.envelope import CorruptedStoreError

        try:
            block = self._block_store.load_block(height)
            commit = self._block_store.load_block_commit(height)
            if commit is None:
                # Tip block: only the seen commit exists so far.
                commit = self._block_store.load_seen_commit(height)
        except CorruptedStoreError as e:
            # quarantined + repair scheduled by the store hook: a light
            # client / statesync consumer must see a clean not-found (it
            # retries another provider) rather than rotten bytes
            raise ErrLightBlockNotFound(
                f"block at height {height} quarantined: {e}") from e
        if block is None or commit is None:
            raise ErrLightBlockNotFound(f"no block at height {height}")
        try:
            vals = self._state_store.load_validators(height)
        except Exception as e:  # StateStoreError -> provider error domain
            raise ErrLightBlockNotFound(f"no validators at height {height}: {e}") from e
        return LightBlock(
            signed_header=SignedHeader(header=block.header, commit=commit),
            validator_set=vals,
        )

    def report_evidence(self, ev) -> None:
        self.evidences.append(ev)


def json_rpc_call(base_url: str, method: str, params: dict,
                  timeout: float = 5.0, rid: int = 1):
    """One JSON-RPC 2.0 POST round trip; raises a ProviderError subclass.

    Error-message taxonomy is part of the wire contract with rpc/core.py's
    light_block route: a lagging node says "must be less" (ErrHeightTooHigh,
    tolerated by the detector as "hasn't caught up"), a pruned/missing block
    says "could not find" (ErrLightBlockNotFound, witness treated as dead).
    Shared by HTTPProvider and light/proxy."""
    body = json.dumps(
        {"jsonrpc": "2.0", "id": rid, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        base_url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = json.loads(resp.read())
    except OSError as e:
        raise ErrNoResponse(str(e)) from e
    if payload.get("error"):
        msg = str(payload["error"])
        if "must be less" in msg:
            raise ErrHeightTooHigh(msg)
        if "not find" in msg or "not found" in msg:
            raise ErrLightBlockNotFound(msg)
        raise ProviderError(msg)
    return payload["result"]


class HTTPProvider(Provider):
    """JSON-RPC provider (reference: light/provider/http/http.go:65).

    Uses this framework's binary `light_block` route: one hex proto
    round-trip instead of the reference's /commit + paginated /validators
    JSON assembly (which needs 1+N/100 requests for an N-validator set).
    """

    def __init__(self, chain_id: str, base_url: str, timeout: float = 5.0):
        self._chain_id = chain_id
        self._base = base_url.rstrip("/")
        self._timeout = timeout
        self._rid = 0

    def chain_id(self) -> str:
        return self._chain_id

    def _call(self, method: str, params: dict):
        self._rid += 1
        return json_rpc_call(self._base, method, params, self._timeout, self._rid)

    def light_block(self, height: int) -> LightBlock:
        params = {} if height == 0 else {"height": str(height)}
        res = self._call("light_block", params)
        try:
            lb = LightBlock.unmarshal(bytes.fromhex(res["light_block"]))
            lb.validate_basic(self._chain_id)
        except (ValueError, KeyError, TypeError) as e:
            raise ErrBadLightBlock(str(e)) from e
        return lb

    def report_evidence(self, ev) -> None:
        self._call("broadcast_evidence", {"evidence": ev.bytes().hex()})
