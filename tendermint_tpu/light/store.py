"""Trusted light-block store (reference: light/store/store.go interface,
light/store/db/db.go implementation).

Persists verified LightBlocks keyed by height. Backed by any
tendermint_tpu.store.db.DB (memdb or sqlite), so a light node's trust state
survives restarts.
"""

from __future__ import annotations

import threading

from tendermint_tpu.store.db import DB, prefix_end
from tendermint_tpu.types.light_block import LightBlock


def _key(height: int) -> bytes:
    return b"lb/" + height.to_bytes(8, "big")


class DBStore:
    """reference: light/store/db/db.go:22 (dbs struct)."""

    def __init__(self, db: DB, prefix: str = ""):
        self._db = db
        self._prefix = prefix.encode() if prefix else b""
        self._mtx = threading.Lock()

    def _k(self, height: int) -> bytes:
        return self._prefix + _key(height)

    # --- Store interface (reference: light/store/store.go:12-44) -----------

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("lightBlock height must be > 0")
        with self._mtx:
            self._db.set(self._k(lb.height), lb.marshal())

    def delete_light_block(self, height: int) -> None:
        if height <= 0:
            raise ValueError("height must be > 0")
        with self._mtx:
            self._db.delete(self._k(height))

    def light_block(self, height: int) -> LightBlock | None:
        if height <= 0:
            raise ValueError("height must be > 0")
        raw = self._db.get(self._k(height))
        if raw is None:
            return None
        return LightBlock.unmarshal(raw)

    def _range(self) -> tuple[bytes, bytes | None]:
        start = self._prefix + b"lb/"
        return start, prefix_end(start)

    def latest_light_block(self) -> LightBlock | None:
        """Keys are fixed-width big-endian, so DB order == height order:
        the latest block is the last key (reference: light/store/db/db.go:114
        does the same with a reverse iterator)."""
        start, end = self._range()
        for k, v in self._db.reverse_iterator(start, end):
            return LightBlock.unmarshal(v)
        return None

    def first_light_block_height(self) -> int:
        start, end = self._range()
        for k, _ in self._db.iterator(start, end):
            return int.from_bytes(k[len(start):], "big")
        return -1

    def light_block_before(self, height: int) -> LightBlock | None:
        """Largest stored height strictly below `height` (reference:
        light/store/db/db.go:168)."""
        start, _ = self._range()
        for _, v in self._db.reverse_iterator(start, self._k(height)):
            return LightBlock.unmarshal(v)
        return None

    def prune(self, size: int) -> None:
        """Keep at most `size` newest blocks (reference:
        light/store/db/db.go:192)."""
        excess = self.size() - size
        if excess <= 0:
            return
        start, end = self._range()
        doomed = []
        for k, _ in self._db.iterator(start, end):
            if len(doomed) >= excess:
                break
            doomed.append(k)
        with self._mtx:
            for k in doomed:
                self._db.delete(k)

    def size(self) -> int:
        start, end = self._range()
        return sum(1 for _ in self._db.iterator(start, end))
