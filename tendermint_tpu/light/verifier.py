"""Pure light-client verification functions (reference: light/verifier.go).

Core semantics preserved exactly:
 - VerifyAdjacent (light/verifier.go:93): trust chained through
   NextValidatorsHash equality + 2/3 of the new set signing.
 - VerifyNonAdjacent (light/verifier.go:32): trustLevel (default 1/3) of the
   TRUSTED set must have signed the new header, then 2/3 of the new set.
 - VerifyBackwards (light/verifier.go:218): hash-linked reverse walk.

TPU angle: both commit checks funnel into the batched BatchVerifier used by
ValidatorSet.verify_commit_light / verify_commit_light_trusting, so one
header verification is at most two kernel flushes, and verify_header_range
(range_verify.py) folds a whole header chain into one flush.
"""

from __future__ import annotations

from tendermint_tpu.types.light_block import SignedHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    ValidatorSet,
)

# New header can be trusted if at least one correct validator signed it
# (reference: light/verifier.go:16 DefaultTrustLevel).
DEFAULT_TRUST_LEVEL = (1, 3)


class LightClientError(Exception):
    pass


class ErrOldHeaderExpired(LightClientError):
    def __init__(self, at: Time, now: Time):
        self.at, self.now = at, now
        super().__init__(f"old header has expired at {at} (now: {now})")


class ErrInvalidHeader(LightClientError):
    def __init__(self, reason):
        self.reason = reason
        super().__init__(f"invalid header: {reason}")


class ErrNewValSetCantBeTrusted(LightClientError):
    def __init__(self, reason):
        self.reason = reason
        super().__init__(
            f"can't trust new val set: {reason}"
        )


def validate_trust_level(lvl: tuple[int, int]) -> None:
    """trustLevel must be within [1/3, 1] (reference: light/verifier.go:196)."""
    num, den = lvl
    if num * 3 < den or num > den or den == 0:
        raise LightClientError(f"trustLevel must be within [1/3, 1], given {num}/{den}")


def header_expired(h: SignedHeader, trusting_period_s: float, now: Time) -> bool:
    """reference: light/verifier.go:206-210."""
    expiration_ns = h.header.time.unix_ns() + int(trusting_period_s * 1e9)
    return expiration_ns <= now.unix_ns()


def _verify_new_header_and_vals(untrusted_header: SignedHeader,
                                untrusted_vals: ValidatorSet,
                                trusted_header: SignedHeader,
                                now: Time, max_clock_drift_s: float) -> None:
    """reference: light/verifier.go:153-193."""
    try:
        untrusted_header.validate_basic(trusted_header.header.chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"untrustedHeader.ValidateBasic failed: {e}") from e
    if untrusted_header.height <= trusted_header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted_header.height} to be greater "
            f"than one of old header {trusted_header.height}"
        )
    if untrusted_header.header.time.unix_ns() <= trusted_header.header.time.unix_ns():
        raise ErrInvalidHeader(
            f"expected new header time {untrusted_header.header.time} to be "
            f"after old header time {trusted_header.header.time}"
        )
    if untrusted_header.header.time.unix_ns() >= now.unix_ns() + int(max_clock_drift_s * 1e9):
        raise ErrInvalidHeader(
            f"new header has a time from the future {untrusted_header.header.time} "
            f"(now: {now}; max clock drift: {max_clock_drift_s}s)"
        )
    vh = untrusted_vals.hash()
    if untrusted_header.header.validators_hash != vh:
        raise ErrInvalidHeader(
            f"expected new header validators ({untrusted_header.header.validators_hash.hex()}) "
            f"to match those that were supplied ({vh.hex()}) at height "
            f"{untrusted_header.height}"
        )


def verify_adjacent(trusted_header: SignedHeader,
                    untrusted_header: SignedHeader,
                    untrusted_vals: ValidatorSet,
                    trusting_period_s: float, now: Time,
                    max_clock_drift_s: float) -> None:
    """reference: light/verifier.go:93-135 VerifyAdjacent."""
    if untrusted_header.height != trusted_header.height + 1:
        raise LightClientError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_s, now):
        raise ErrOldHeaderExpired(
            Time.from_unix_ns(trusted_header.header.time.unix_ns()
                              + int(trusting_period_s * 1e9)), now)
    _verify_new_header_and_vals(untrusted_header, untrusted_vals,
                                trusted_header, now, max_clock_drift_s)
    if untrusted_header.header.validators_hash != trusted_header.header.next_validators_hash:
        raise LightClientError(
            f"expected old header next validators "
            f"({trusted_header.header.next_validators_hash.hex()}) to match those "
            f"from new header ({untrusted_header.header.validators_hash.hex()})"
        )
    try:
        untrusted_vals.verify_commit_light(
            trusted_header.header.chain_id, untrusted_header.commit.block_id,
            untrusted_header.height, untrusted_header.commit)
    except Exception as e:  # noqa: BLE001 - wrap like the reference
        raise ErrInvalidHeader(e) from e


def verify_non_adjacent(trusted_header: SignedHeader, trusted_vals: ValidatorSet,
                        untrusted_header: SignedHeader,
                        untrusted_vals: ValidatorSet,
                        trusting_period_s: float, now: Time,
                        max_clock_drift_s: float,
                        trust_level: tuple[int, int] = DEFAULT_TRUST_LEVEL) -> None:
    """reference: light/verifier.go:32-90 VerifyNonAdjacent."""
    if untrusted_header.height == trusted_header.height + 1:
        raise LightClientError("headers must be non adjacent in height")
    if header_expired(trusted_header, trusting_period_s, now):
        raise ErrOldHeaderExpired(
            Time.from_unix_ns(trusted_header.header.time.unix_ns()
                              + int(trusting_period_s * 1e9)), now)
    _verify_new_header_and_vals(untrusted_header, untrusted_vals,
                                trusted_header, now, max_clock_drift_s)
    # trustLevel (default 1/3) of the trusted validators must have signed.
    try:
        trusted_vals.verify_commit_light_trusting(
            trusted_header.header.chain_id, untrusted_header.commit, trust_level)
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(e) from e
    # 2/3 of the new validators must have signed. Kept last: untrustedVals
    # can be made large to DOS the light client (reference comment :69-72).
    try:
        untrusted_vals.verify_commit_light(
            trusted_header.header.chain_id, untrusted_header.commit.block_id,
            untrusted_header.height, untrusted_header.commit)
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(e) from e


def verify(trusted_header: SignedHeader, trusted_vals: ValidatorSet,
           untrusted_header: SignedHeader, untrusted_vals: ValidatorSet,
           trusting_period_s: float, now: Time, max_clock_drift_s: float,
           trust_level: tuple[int, int] = DEFAULT_TRUST_LEVEL) -> None:
    """reference: light/verifier.go:137-151 Verify."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(trusted_header, trusted_vals, untrusted_header,
                            untrusted_vals, trusting_period_s, now,
                            max_clock_drift_s, trust_level)
    else:
        verify_adjacent(trusted_header, untrusted_header, untrusted_vals,
                        trusting_period_s, now, max_clock_drift_s)


def verify_backwards(untrusted_header, trusted_header) -> None:
    """Headers, not SignedHeaders (reference: light/verifier.go:218-244)."""
    try:
        untrusted_header.validate_basic()
    except ValueError as e:
        raise ErrInvalidHeader(e) from e
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if untrusted_header.time.unix_ns() >= trusted_header.time.unix_ns():
        raise ErrInvalidHeader(
            f"expected older header time {untrusted_header.time} to be before "
            f"new header time {trusted_header.time}"
        )
    if trusted_header.last_block_id.hash != untrusted_header.hash():
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.hash().hex()} does not match "
            f"trusted header's last block {trusted_header.last_block_id.hash.hex()}"
        )
