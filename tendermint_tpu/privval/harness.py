"""Remote-signer validation harness — the operator tool the reference ships
as tools/tm-signer-harness (docs/tools/remote-signer-validation.md; r4
verdict missing #4).

Runs a privval listener, waits for the remote signer (KMS-style deployment)
to dial in, and executes the compatibility checks:

  1. PING round trip
  2. PubKeyRequest — and, when a local priv_validator_key.json or genesis
     is given, that the remote key MATCHES the expected validator key
  3. SignProposalRequest — signature verifies over the canonical proposal
     sign bytes
  4. SignVoteRequest (prevote + precommit) — signatures verify; an
     identical re-sign returns the same signature (idempotent double-sign
     protection); a REGRESSING request (lower round) is refused with a
     RemoteSignerError (FilePV CheckHRS semantics)

Exit codes mirror the reference harness's failure classes
(tools/tm-signer-harness/main.go): 0 success, 1 connection/setup failure,
2 key mismatch, 3 proposal signature failure, 4 vote signature failure.
"""

from __future__ import annotations

import json
import os

from tendermint_tpu.privval.signer import (
    RemoteSignerError,
    SignerClient,
    SignerListenerEndpoint,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote

EXIT_OK = 0
EXIT_CONNECT = 1
EXIT_KEY_MISMATCH = 2
EXIT_PROPOSAL_SIG = 3
EXIT_VOTE_SIG = 4


def _expected_pubkey(home: str | None):
    """Expected validator pubkey bytes from priv_validator_key.json, or
    None when no home is given."""
    if not home:
        return None
    path = os.path.join(home, "config", "priv_validator_key.json")
    if not os.path.exists(path):
        return None
    from tendermint_tpu.privval.file_pv import FilePV

    pv = FilePV.load(path, os.devnull)
    return pv.get_pub_key().bytes()


def run_harness(laddr: str, chain_id: str, home: str | None = None,
                accept_timeout_s: float = 30.0, log=print) -> int:
    """Listen on laddr, validate the remote signer that dials in. Returns
    an exit code (see module docstring)."""
    bid = BlockID(hash=b"\xab" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\xcd" * 32))
    try:
        ep = SignerListenerEndpoint(laddr, accept_timeout_s=accept_timeout_s)
        client = SignerClient(ep, chain_id)
        if not client.ping():
            log("FAILED: no PING response from remote signer")
            return EXIT_CONNECT
        log("remote signer connected; PING ok")
    except Exception as e:  # noqa: BLE001 - report, exit with connect code
        log(f"FAILED: remote signer never connected: {e}")
        return EXIT_CONNECT

    try:
        pub = client.get_pub_key()
        log(f"remote pubkey: {pub.type}/{pub.bytes().hex()}")
        expected = _expected_pubkey(home)
        if expected is not None and expected != pub.bytes():
            log("FAILED: remote signer key does not match "
                "priv_validator_key.json")
            return EXIT_KEY_MISMATCH

        # proposal signature over canonical sign bytes
        prop = Proposal(type=32, height=1, round=0, pol_round=-1,
                        block_id=bid, timestamp=Time(1_700_000_000, 0))
        client.sign_proposal(chain_id, prop)
        if not pub.verify_signature(prop.sign_bytes(chain_id),
                                    prop.signature):
            log("FAILED: proposal signature does not verify")
            return EXIT_PROPOSAL_SIG
        log("proposal signature ok")

        # votes: prevote then precommit, idempotent re-sign, HRS regression
        sigs = {}
        for vtype, name in ((PREVOTE_TYPE, "prevote"),
                            (PRECOMMIT_TYPE, "precommit")):
            vote = Vote(type=vtype, height=2, round=1, block_id=bid,
                        timestamp=Time(1_700_000_001, 0),
                        validator_address=pub.address(), validator_index=0)
            client.sign_vote(chain_id, vote)
            if not pub.verify_signature(vote.sign_bytes(chain_id),
                                        vote.signature):
                log(f"FAILED: {name} signature does not verify")
                return EXIT_VOTE_SIG
            sigs[vtype] = (vote.signature, vote.timestamp)
            again = Vote(type=vtype, height=2, round=1, block_id=bid,
                        timestamp=Time(1_700_000_001, 0),
                        validator_address=pub.address(), validator_index=0)
            client.sign_vote(chain_id, again)
            if again.signature != vote.signature:
                log(f"FAILED: {name} re-sign of the identical vote returned "
                    "a different signature (double-sign hazard)")
                return EXIT_VOTE_SIG
            log(f"{name} signature ok (idempotent re-sign)")
        regress = Vote(type=PREVOTE_TYPE, height=2, round=0, block_id=bid,
                       timestamp=Time(1_700_000_002, 0),
                       validator_address=pub.address(), validator_index=0)
        try:
            client.sign_vote(chain_id, regress)
            log("FAILED: remote signer signed a ROUND-REGRESSING vote")
            return EXIT_VOTE_SIG
        except RemoteSignerError:
            log("round regression refused ok")
        log("remote signer validation PASSED")
        return EXIT_OK
    except RemoteSignerError as e:
        log(f"FAILED: remote signer error: {e}")
        return EXIT_VOTE_SIG
    except Exception as e:  # noqa: BLE001
        log(f"FAILED: {e}")
        return EXIT_CONNECT
    finally:
        try:
            ep.close()
        except Exception:  # noqa: BLE001
            pass


def summary_json(code: int) -> str:
    names = {EXIT_OK: "ok", EXIT_CONNECT: "connect_failed",
             EXIT_KEY_MISMATCH: "key_mismatch",
             EXIT_PROPOSAL_SIG: "proposal_sig_failed",
             EXIT_VOTE_SIG: "vote_sig_failed"}
    return json.dumps({"exit_code": code, "result": names.get(code, "unknown")})
