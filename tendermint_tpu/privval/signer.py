"""Remote signer protocol (reference: privval/signer_client.go:16,
privval/signer_listener_endpoint.go, privval/signer_server.go,
privval/signer_dialer_endpoint.go, proto/tendermint/privval/types.proto).

Key isolation: the validator's private key lives in a separate signer
process. The NODE listens on privval_laddr; the SIGNER dials in (so the key
box needs no open ports), then the node sends sign requests over that
connection:

  node  SignerListenerEndpoint + SignerClient (PrivValidator impl)
  signer SignerServer wrapping a FilePV, dials the node

Message oneof (reference proto field numbers):
  PubKeyRequest=1{chain_id=1}  PubKeyResponse=2{pub_key=1, error=2}
  SignVoteRequest=3{vote=1, chain_id=2}  SignedVoteResponse=4{vote=1, error=2}
  SignProposalRequest=5{proposal=1, chain_id=2}
  SignedProposalResponse=6{proposal=1, error=2}  PingRequest=7  PingResponse=8
RemoteSignerError{code=1, description=2}.
"""

from __future__ import annotations

import socket
import threading
import time

from tendermint_tpu.crypto import keys
from tendermint_tpu.encoding import proto
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.utils import log


class RemoteSignerError(Exception):
    def __init__(self, code: int, description: str):
        self.code = code
        self.description = description
        super().__init__(f"signer error (code {code}): {description}")


# --- framing (uvarint length-delimited, like ABCI) --------------------------


def _write_msg(wfile, msg: bytes) -> None:
    wfile.write(proto.encode_uvarint(len(msg)) + msg)
    wfile.flush()


def _read_msg(rfile) -> bytes | None:
    shift = 0
    length = 0
    while True:
        b = rfile.read(1)
        if not b:
            return None
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("bad length prefix")
    if length > 1 << 20:
        raise ValueError("privval message too large")
    out = b""
    while len(out) < length:
        chunk = rfile.read(length - len(out))
        if not chunk:
            raise EOFError("truncated privval message")
        out += chunk
    return out


# --- message codecs ---------------------------------------------------------


def _pubkey_marshal(pub: keys.PubKey) -> bytes:
    # The types/validator.py PublicKey oneof (ed25519=1, secp256k1=2, plus
    # the documented sr25519=3 extension). An unknown key type raises --
    # defaulting to field 1 would make the node unmarshal it as ed25519:
    # wrong address, every verify fails silently.
    from tendermint_tpu.types.validator import pubkey_proto_bytes

    return pubkey_proto_bytes(pub)


def _pubkey_unmarshal(buf: bytes) -> keys.PubKey:
    from tendermint_tpu.types.validator import pubkey_from_proto_bytes

    try:
        return pubkey_from_proto_bytes(buf)
    except ValueError:
        raise ValueError("empty remote-signer pubkey") from None


def _error_marshal(e: RemoteSignerError) -> bytes:
    return proto.Writer().varint(1, e.code).string(2, e.description).out()


def _maybe_error(f: dict, fieldnum: int) -> None:
    if fieldnum in f:
        ef = proto.fields(f[fieldnum][-1])
        raise RemoteSignerError(
            proto.as_sint64(ef.get(1, [0])[-1]),
            ef.get(2, [b""])[-1].decode() if 2 in ef else "")


def msg_pubkey_request(chain_id: str) -> bytes:
    inner = proto.Writer().string(1, chain_id).out()
    return proto.Writer().message(1, inner, always=True).out()


def msg_sign_vote_request(chain_id: str, vote: Vote) -> bytes:
    inner = (proto.Writer().message(1, vote.marshal(), always=True)
             .string(2, chain_id).out())
    return proto.Writer().message(3, inner, always=True).out()


def msg_sign_proposal_request(chain_id: str, p: Proposal) -> bytes:
    inner = (proto.Writer().message(1, p.marshal(), always=True)
             .string(2, chain_id).out())
    return proto.Writer().message(5, inner, always=True).out()


def msg_ping_request() -> bytes:
    return proto.Writer().message(7, b"", always=True).out()


# --- signer side ------------------------------------------------------------


class SignerServer:
    """Wraps a PrivValidator and serves sign requests; DIALS the node
    (reference: privval/signer_server.go + signer_dialer_endpoint.go)."""

    def __init__(self, priv_validator, addr: str,
                 retries: int = 40, retry_interval_s: float = 0.25,
                 logger=None):
        self.pv = priv_validator
        self.addr = addr
        self.retries = retries
        self.retry_interval_s = retry_interval_s
        # loud by default — a remote signer that silently stops signing is
        # a validator outage; pass log.NopLogger() to silence
        self.logger = (logger if logger is not None
                       else log.Logger().with_(module="privval"))
        self._running = False
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, name="signer-server",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _dial(self) -> socket.socket | None:
        host, port = self.addr.split("://", 1)[1].rsplit(":", 1)
        for _ in range(self.retries):
            if not self._running:
                return None
            try:
                return socket.create_connection((host, int(port)), timeout=5.0)
            except OSError:
                time.sleep(self.retry_interval_s)
        return None

    def _run(self) -> None:
        while self._running:
            sock = self._dial()
            if sock is None:
                return
            self._sock = sock
            try:
                self._serve(sock)
            except Exception as e:  # noqa: BLE001 - malformed requests must
                # not end the signer permanently; drop the conn and re-dial —
                # loudly, or a validator that silently stops signing (every
                # conn dying on a systematic decode bug) has no trail
                if self.logger:
                    self.logger.error("signer connection dropped",
                                      addr=self.addr, err=e)
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            # connection lost: re-dial unless stopping

    def _serve(self, sock: socket.socket) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        while self._running:
            buf = _read_msg(rfile)
            if buf is None:
                return
            _write_msg(wfile, self._handle(buf))

    def _handle(self, buf: bytes) -> bytes:
        """reference: privval/signer_requestHandler.go DefaultValidationRequestHandler."""
        f = proto.fields(buf)
        w = proto.Writer()
        if 1 in f:  # PubKeyRequest
            try:
                pub = self.pv.get_pub_key()
                inner = proto.Writer().message(
                    1, _pubkey_marshal(pub), always=True).out()
            except Exception as e:  # noqa: BLE001 - e.g. non-proto key type
                # Reply with the PubKeyResponse error field: raising here
                # would close the socket and silently re-dial forever.
                inner = proto.Writer().message(
                    2, _error_marshal(RemoteSignerError(4, str(e))),
                    always=True).out()
            return w.message(2, inner, always=True).out()
        if 3 in f:  # SignVoteRequest
            m = proto.fields(f[3][-1])
            vote = Vote.unmarshal(m.get(1, [b""])[-1])
            chain_id = m.get(2, [b""])[-1].decode() if 2 in m else ""
            try:
                self.pv.sign_vote(chain_id, vote)
                inner = proto.Writer().message(1, vote.marshal(), always=True).out()
            except Exception as e:  # noqa: BLE001 - double-sign guard etc.
                inner = proto.Writer().message(
                    2, _error_marshal(RemoteSignerError(1, str(e))), always=True).out()
            return w.message(4, inner, always=True).out()
        if 5 in f:  # SignProposalRequest
            m = proto.fields(f[5][-1])
            prop = Proposal.unmarshal(m.get(1, [b""])[-1])
            chain_id = m.get(2, [b""])[-1].decode() if 2 in m else ""
            try:
                self.pv.sign_proposal(chain_id, prop)
                inner = proto.Writer().message(1, prop.marshal(), always=True).out()
            except Exception as e:  # noqa: BLE001
                inner = proto.Writer().message(
                    2, _error_marshal(RemoteSignerError(2, str(e))), always=True).out()
            return w.message(6, inner, always=True).out()
        if 7 in f:  # PingRequest
            return w.message(8, b"", always=True).out()
        # unknown request -> error response in a PubKeyResponse envelope
        inner = proto.Writer().message(
            2, _error_marshal(RemoteSignerError(3, "unknown request")), always=True).out()
        return w.message(2, inner, always=True).out()


# --- node side --------------------------------------------------------------


class SignerListenerEndpoint:
    """Listens for the signer's inbound connection (reference:
    privval/signer_listener_endpoint.go)."""

    def __init__(self, laddr: str, timeout_s: float = 5.0,
                 accept_timeout_s: float = 30.0):
        self.timeout_s = timeout_s
        self.accept_timeout_s = accept_timeout_s
        host, port = laddr.split("://", 1)[1].rsplit(":", 1)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(1)
        h, p = self._listener.getsockname()[:2]
        self.laddr = f"tcp://{h}:{p}"
        self._conn: socket.socket | None = None
        self._rfile = None
        self._wfile = None
        self._mtx = threading.Lock()

    def _ensure_connection(self) -> None:
        if self._conn is not None:
            return
        self._listener.settimeout(self.accept_timeout_s)
        conn, _ = self._listener.accept()
        conn.settimeout(self.timeout_s)
        self._conn = conn
        self._rfile = conn.makefile("rb")
        self._wfile = conn.makefile("wb")

    def _drop_connection(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        self._conn = None

    def send_request(self, msg: bytes) -> bytes:
        with self._mtx:
            self._ensure_connection()
            try:
                _write_msg(self._wfile, msg)
                resp = _read_msg(self._rfile)
            except (OSError, EOFError) as e:
                self._drop_connection()
                raise ConnectionError(f"remote signer connection failed: {e}") from e
            if resp is None:
                self._drop_connection()
                raise ConnectionError("remote signer closed the connection")
            return resp

    def close(self) -> None:
        with self._mtx:
            self._drop_connection()
            try:
                self._listener.close()
            except OSError:
                pass


class SignerClient:
    """PrivValidator over a remote signer endpoint (reference:
    privval/signer_client.go:16)."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._cached_pub: keys.PubKey | None = None

    def ping(self) -> bool:
        try:
            f = proto.fields(self.endpoint.send_request(msg_ping_request()))
            return 8 in f
        except ConnectionError:
            return False

    def get_pub_key(self) -> keys.PubKey:
        if self._cached_pub is None:
            f = proto.fields(self.endpoint.send_request(
                msg_pubkey_request(self.chain_id)))
            if 2 not in f:
                raise RemoteSignerError(3, "unexpected response to PubKeyRequest")
            m = proto.fields(f[2][-1])
            _maybe_error(m, 2)
            self._cached_pub = _pubkey_unmarshal(m.get(1, [b""])[-1])
        return self._cached_pub

    def get_address(self) -> bytes:
        return self.get_pub_key().address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        f = proto.fields(self.endpoint.send_request(
            msg_sign_vote_request(chain_id, vote)))
        if 4 not in f:
            raise RemoteSignerError(3, "unexpected response to SignVoteRequest")
        m = proto.fields(f[4][-1])
        _maybe_error(m, 2)
        signed = Vote.unmarshal(m.get(1, [b""])[-1])
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        f = proto.fields(self.endpoint.send_request(
            msg_sign_proposal_request(chain_id, proposal)))
        if 6 not in f:
            raise RemoteSignerError(3, "unexpected response to SignProposalRequest")
        m = proto.fields(f[6][-1])
        _maybe_error(m, 2)
        signed = Proposal.unmarshal(m.get(1, [b""])[-1])
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp


class RetrySignerClient:
    """Retries transient connection failures (reference:
    privval/retry_signer_client.go). RemoteSignerError (e.g. the double-sign
    guard) is NOT retried -- retrying a refusal would be unsafe."""

    def __init__(self, client: SignerClient, retries: int = 5,
                 interval_s: float = 0.2):
        self.client = client
        self.retries = retries
        self.interval_s = interval_s

    def _retry(self, fn, *args):
        last: Exception | None = None
        for _ in range(self.retries):
            try:
                return fn(*args)
            except ConnectionError as e:
                last = e
                time.sleep(self.interval_s)
        raise last

    def get_pub_key(self) -> keys.PubKey:
        return self._retry(self.client.get_pub_key)

    def get_address(self) -> bytes:
        return self._retry(self.client.get_address)

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        return self._retry(self.client.sign_vote, chain_id, vote)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        return self._retry(self.client.sign_proposal, chain_id, proposal)
