"""FilePV: file-backed private validator with double-sign protection
(reference: privval/file.go:75,92,300-341).

Split into a key file (immutable) and a last-sign-state file (fsync'd before
every signature release) exactly like the reference, so a crash between sign
and broadcast can never produce conflicting signatures on restart.
"""

from __future__ import annotations

import base64
import json
import os
import tempfile
from dataclasses import dataclass, field

from tendermint_tpu.crypto import ed25519, keys
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, PROPOSAL_TYPE, Vote

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type == PREVOTE_TYPE:
        return STEP_PREVOTE
    if vote.type == PRECOMMIT_TYPE:
        return STEP_PRECOMMIT
    raise ValueError(f"Unknown vote type: {vote.type}")


class DoubleSignError(Exception):
    pass


@dataclass
class FilePVLastSignState:
    """reference: privval/file.go:75-130."""

    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if we have signed EXACTLY this HRS before (caller may
        re-sign iff sign-bytes match modulo timestamp). Raises on regression
        (reference: privval/file.go:92-130)."""
        if self.height > height:
            raise DoubleSignError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}. Got {round_}, last round {self.round}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no SignBytes found")
                    if not self.signature:
                        raise AssertionError("pv: Signature is nil but SignBytes is not!")
                    return True
        return False

    def save(self) -> None:
        """Atomic write + fsync (the double-sign guard depends on this)."""
        doc = {
            "height": str(self.height),
            "round": self.round,
            "step": self.step,
            "signature": base64.b64encode(self.signature).decode() if self.signature else None,
            "signbytes": self.sign_bytes.hex().upper() if self.sign_bytes else None,
        }
        _atomic_write_json(self.file_path, doc)

    @staticmethod
    def load(path: str) -> "FilePVLastSignState":
        with open(path) as f:
            doc = json.load(f)
        return FilePVLastSignState(
            height=int(doc.get("height", 0)),
            round=int(doc.get("round", 0)),
            step=int(doc.get("step", 0)),
            signature=base64.b64decode(doc["signature"]) if doc.get("signature") else b"",
            sign_bytes=bytes.fromhex(doc["signbytes"]) if doc.get("signbytes") else b"",
            file_path=path,
        )


class FilePV:
    """reference: privval/file.go:132-341."""

    def __init__(self, priv_key: keys.PrivKey, key_file_path: str, state_file_path: str):
        self.priv_key = priv_key
        self.key_file_path = key_file_path
        self.last_sign_state = FilePVLastSignState(file_path=state_file_path)

    # --- construction ------------------------------------------------------

    @staticmethod
    def generate(key_file_path: str, state_file_path: str, seed: bytes | None = None) -> "FilePV":
        pv = FilePV(ed25519.gen_priv_key(seed), key_file_path, state_file_path)
        pv.save()
        return pv

    @staticmethod
    def load(key_file_path: str, state_file_path: str) -> "FilePV":
        with open(key_file_path) as f:
            doc = json.load(f)
        kt = doc["priv_key"]["type"]
        kb = base64.b64decode(doc["priv_key"]["value"])
        name = {"tendermint/PrivKeyEd25519": "ed25519"}.get(kt, kt)
        priv = keys.privkey_from_type_bytes(name, kb)
        pv = FilePV(priv, key_file_path, state_file_path)
        if os.path.exists(state_file_path) and os.path.getsize(state_file_path) > 0:
            pv.last_sign_state = FilePVLastSignState.load(state_file_path)
        else:
            pv.last_sign_state.save()
        return pv

    @staticmethod
    def load_or_generate(key_file_path: str, state_file_path: str) -> "FilePV":
        if os.path.exists(key_file_path):
            return FilePV.load(key_file_path, state_file_path)
        return FilePV.generate(key_file_path, state_file_path)

    def save(self) -> None:
        pub = self.priv_key.pub_key()
        doc = {
            "address": pub.address().hex().upper(),
            "pub_key": {
                "type": "tendermint/PubKeyEd25519",
                "value": base64.b64encode(pub.bytes()).decode(),
            },
            "priv_key": {
                "type": "tendermint/PrivKeyEd25519",
                "value": base64.b64encode(self.priv_key.bytes()).decode(),
            },
        }
        _atomic_write_json(self.key_file_path, doc)
        self.last_sign_state.save()

    # --- PrivValidator interface (reference: types/priv_validator.go) ------

    def get_pub_key(self) -> keys.PubKey:
        return self.priv_key.pub_key()

    def get_address(self) -> bytes:
        return self.priv_key.pub_key().address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Sets vote.signature (and possibly reuses timestamp); raises on
        double-sign (reference: privval/file.go:300-341 signVote)."""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
                return
            ts = _extract_vote_timestamp(lss.sign_bytes, chain_id, vote)
            if ts is not None:
                # Same vote modulo timestamp: re-sign with the PREVIOUS
                # timestamp (reference behavior).
                vote.timestamp = ts
                vote.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """reference: privval/file.go:343-391."""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
                return
            ts = _extract_proposal_timestamp(lss.sign_bytes, chain_id, proposal)
            if ts is not None:
                proposal.timestamp = ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError("conflicting data")

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        proposal.signature = sig

    def _save_signed(self, height: int, round_: int, step: int,
                     sign_bytes: bytes, sig: bytes) -> None:
        lss = self.last_sign_state
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature, lss.sign_bytes = sig, sign_bytes
        lss.save()


def _extract_vote_timestamp(last_sign_bytes: bytes, chain_id: str, vote: Vote) -> Time | None:
    """If last_sign_bytes equals vote's sign-bytes modulo timestamp, return
    the last timestamp (reference: privval/utils checkVotesOnlyDifferByTimestamp)."""
    from tendermint_tpu.encoding import proto as p
    from tendermint_tpu.types.vote import canonical_vote_bytes

    try:
        body, _ = p.parse_delimited(last_sign_bytes)
        f = p.fields(body)
        ts = Time.unmarshal(f.get(5, [b""])[-1])
    except Exception:  # noqa: BLE001
        return None
    trial = canonical_vote_bytes(chain_id, vote.type, vote.height, vote.round,
                                 vote.block_id, ts)
    return ts if trial == last_sign_bytes else None


def _extract_proposal_timestamp(last_sign_bytes: bytes, chain_id: str,
                                proposal: Proposal) -> Time | None:
    from tendermint_tpu.encoding import proto as p
    from tendermint_tpu.types.proposal import canonical_proposal_bytes

    try:
        body, _ = p.parse_delimited(last_sign_bytes)
        f = p.fields(body)
        ts = Time.unmarshal(f.get(6, [b""])[-1])
    except Exception:  # noqa: BLE001
        return None
    trial = canonical_proposal_bytes(chain_id, proposal.height, proposal.round,
                                     proposal.pol_round, proposal.block_id, ts)
    return ts if trial == last_sign_bytes else None


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".pv-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class MockPV:
    """In-process test signer (reference: types/priv_validator.go MockPV)."""

    def __init__(self, priv_key=None):
        self.priv_key = priv_key if priv_key is not None else ed25519.gen_priv_key()

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def get_address(self):
        return self.priv_key.pub_key().address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        vote.signature = self.priv_key.sign(vote.sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        proposal.signature = self.priv_key.sign(proposal.sign_bytes(chain_id))
