"""Multi-chip sharding of the batch-verify + tally kernel.

The reference's parallelism analogue (SURVEY.md section 2.3): inside one
validator process, the signature batch for a commit is data-parallel over the
validator axis. We shard that axis across TPU devices with shard_map over a
1-D ("dp",) mesh; the per-device pass/fail bitmaps stay sharded and the
voting-power tally is all-reduced over ICI with psum - the on-device analogue
of the reference's libs/bits.BitArray + talliedVotingPower loop
(types/validator_set.go:685-714).

Production routing (docs/PARALLEL.md): both kernel ops modules
(ops/ed25519_batch, ops/sr25519_batch) ask :func:`should_shard` at dispatch
time, so every caller of the BatchVerifier registry -- verify_commit_async,
the fast-sync verify-ahead pipeline, the consensus vote drain, light
range_verify -- gets multi-device sharding transparently through the deferred
dispatch()/PendingVerify contract. With the continuous-batching verify
service on (crypto/verify_service.py, the default), the size
:func:`should_shard` sees is the COALESCED generation -- several callers'
concurrent dispatches merged into one launch -- so multi-caller traffic
crosses the sharding threshold sooner than any single caller would. Knobs:

  TM_TPU_SHARD=0       opt out of sharding entirely (single-device paths)
  TM_TPU_SHARD_MIN=N   batch-size floor for the sharded route (default
                       n_devices * MIN_BUCKET: below one kernel bucket per
                       device the fan-out cannot pay for itself)
  TM_TPU_DISABLE_SHARD=1  legacy alias for TM_TPU_SHARD=0
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops import ed25519_batch

try:
    _shard_map = jax.shard_map  # jax >= 0.5
except AttributeError:  # older jax ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


# ---------------------------------------------------------------------------
# Shard-routing policy (shared by every kernel ops module)
# ---------------------------------------------------------------------------


def shard_enabled() -> bool:
    """False when the operator opted out (TM_TPU_SHARD=0, or the legacy
    TM_TPU_DISABLE_SHARD=1 the dryrun harness has always used)."""
    if os.environ.get("TM_TPU_SHARD") == "0":
        return False
    return os.environ.get("TM_TPU_DISABLE_SHARD") != "1"


def shard_threshold(ndev: int) -> int:
    """Batch-size floor for the sharded route. Default: one kernel MIN_BUCKET
    per device -- smaller batches cannot fill the mesh, and the per-device
    dispatch overhead would exceed the fan-out win."""
    v = os.environ.get("TM_TPU_SHARD_MIN")
    if v:
        return int(v)
    return ndev * ed25519_batch.MIN_BUCKET


def should_shard(n: int) -> bool:
    """THE routing decision both kernel dispatch_batch entry points consult:
    >1 local device, sharding not opted out, and the batch at or above the
    threshold. On 1 device this is always False, so every path behaves
    exactly as the single-device build."""
    ndev = jax.local_device_count()
    return ndev > 1 and shard_enabled() and n >= shard_threshold(ndev)


def make_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), ("dp",))


def _local_verify_tally(tab, h_win, s_win, r_y, r_sign, valid, power, for_block):
    ok = ed25519_batch._verify_kernel(
        tab, h_win, s_win, r_y, r_sign, valid, axis_name="dp"
    )
    # Tally voting power of passing, block-committing signatures; psum over
    # the device mesh so every chip holds the global tally.
    local = jnp.sum(jnp.where(ok & for_block, power, 0))
    tally = jax.lax.psum(local, "dp")
    all_ok = jax.lax.psum(jnp.sum(~ok & valid), "dp") == 0
    return ok, tally, all_ok


def sharded_verify_tally(mesh: Mesh):
    """Build the jitted multi-chip verify+tally step for `mesh`.

    Inputs are sharded on the signature axis; outputs: (bitmap (N,) sharded,
    global tally scalar, global all-valid-passed scalar)."""
    spec = P("dp")
    fn = _shard_map(
        _local_verify_tally,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec, spec),
        out_specs=(spec, P(), P()),
    )
    return jax.jit(fn)


def shard_args(mesh: Mesh, args: dict, power, for_block):
    """Device-put prepared numpy args with the dp sharding layout."""
    spec = NamedSharding(mesh, P("dp"))
    out = {k: jax.device_put(v, spec) for k, v in args.items()}
    out["power"] = jax.device_put(power, spec)
    out["for_block"] = jax.device_put(for_block, spec)
    return out


# ---------------------------------------------------------------------------
# Production path: Ed25519BatchVerifier routes here when >1 device
# ---------------------------------------------------------------------------

_mesh_cache: tuple[tuple, Mesh] | None = None
_fn_cache: dict[tuple, object] = {}


def _get_mesh() -> Mesh:
    global _mesh_cache
    devs = tuple(jax.devices())
    if _mesh_cache is None or _mesh_cache[0] != devs:
        _mesh_cache = (devs, make_mesh(list(devs)))
    return _mesh_cache[1]


def _local_verify(tab_full, idx, h_win, s_win, r_y, r_sign, valid):
    """Per-device ed25519 body: gather this shard's comb tables from the
    replicated key-set table, then run the verify kernel. Gathering INSIDE
    shard_map keeps the per-call H2D payload to indices + scalars; the
    (heavy, height-persistent) tables replicate once per validator set."""
    tab = jnp.take(tab_full, idx, axis=0)
    return ed25519_batch._verify_kernel(
        tab, h_win, s_win, r_y, r_sign, valid, axis_name="dp")


def _local_verify_sr(tab_full, idx, k_win, s_win, r_limbs, valid):
    """Per-device sr25519 body: same replicated-table gather, schnorrkel
    kernel (ops/sr25519_batch; the challenge k stands in for h)."""
    from tendermint_tpu.ops import sr25519_batch

    tab = jnp.take(tab_full, idx, axis=0)
    return sr25519_batch._sr_verify_kernel(
        tab, k_win, s_win, r_limbs, valid, axis_name="dp")


# kind -> (per-device body, number of sharded args: idx + per-item arrays).
# The count is declared, not introspected: a later signature change (default
# arg, decorator) must force this table to be updated in the same edit.
_BODIES = {"ed25519": (_local_verify, 6), "sr25519": (_local_verify_sr, 5)}


def _sharded_verify_fn(mesh: Mesh, kind: str = "ed25519"):
    body, n_item_args = _BODIES[kind]
    key = (kind,) + tuple(id(d) for d in mesh.devices.flat)
    fn = _fn_cache.get(key)
    if fn is None:
        fn = jax.jit(_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(),) + (P("dp"),) * n_item_args,
            out_specs=P("dp"),
        ))
        _fn_cache[key] = fn
        if len(_fn_cache) > 8:
            _fn_cache.pop(next(iter(_fn_cache)))
    return fn


def replicated_tables(ks, mesh: Mesh):
    """The key set's comb tables replicated across the mesh, cached on the
    KeySet (validator sets persist across heights; replication is one-time)."""
    cached = ks.replicated
    key = tuple(id(d) for d in mesh.devices.flat)
    if cached is not None and cached[0] == key:
        return cached[1]
    tab = jax.device_put(ks.tab_ext, NamedSharding(mesh, P()))
    ks.replicated = (key, tab)
    return tab


def _count_sharded_dispatch(ndev: int) -> None:
    from tendermint_tpu.utils import metrics as tmmetrics

    if tmmetrics.GLOBAL_NODE_METRICS is not None:
        tmmetrics.GLOBAL_NODE_METRICS.verify_sharded.add(devices=ndev)


def dispatch_sharded(kind: str, ks, key_idx, arrays: list, n: int):
    """Generic multi-device production dispatch: the signature axis shards
    over the ("dp",) mesh (the north-star sentence: validator sets sharded
    across TPU cores, pass/fail bitmap all-reduced). Dispatches in fixed
    n_devices*JNP_TILE chunks so no batch size triggers a fresh compile;
    padding lanes carry valid=False (every kernel masks its result with
    `valid`, so they can never read as accepted) and key index 0.

    `arrays` is the kernel-specific per-item numpy argument list, valid
    LAST (ed25519: h_win, s_win, r_y, r_sign, valid; sr25519: k_win, s_win,
    r_limbs, valid). Returns the (Npad,) bool device array without fetching
    (callers batch the readback); the bitmap is byte-identical to the
    single-device path."""
    from tendermint_tpu.utils import trace as _trace

    if _trace.ENABLED:
        tr = _trace.current()
        if tr.enabled:
            with tr.span("verify.shard_dispatch", kind=kind, n=n):
                return _dispatch_sharded(kind, ks, key_idx, arrays, n)
    return _dispatch_sharded(kind, ks, key_idx, arrays, n)


def _dispatch_sharded(kind: str, ks, key_idx, arrays: list, n: int):
    import numpy as np

    mesh = _get_mesh()
    ndev = mesh.devices.size
    tile = ed25519_batch.JNP_TILE
    chunk = ndev * tile
    nb = -(-n // chunk) * chunk

    def pad(v):
        out = np.zeros((nb,) + v.shape[1:], dtype=v.dtype)
        out[:n] = v
        return out

    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx
    padded = [pad(np.asarray(v)) for v in arrays]

    tab_full = replicated_tables(ks, mesh)
    fn = _sharded_verify_fn(mesh, kind)
    spec = NamedSharding(mesh, P("dp"))
    outs = []
    for off in range(0, nb, chunk):
        sl = slice(off, off + chunk)
        outs.append(fn(
            tab_full,
            jax.device_put(idx[sl], spec),
            *(jax.device_put(v[sl], spec) for v in padded),
        ))
    _count_sharded_dispatch(ndev)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def dispatch_batch_sharded(ks, key_idx, items, pub_ok):
    """ed25519 sharded dispatch (the original production entry): host prep
    here, then the generic chunked shard_map driver."""
    import numpy as np

    s = ed25519_batch.prepare_scalars(items, pub_ok, windows=True)
    r_y, r_sign = ed25519_batch._r_to_limbs(s["r32"])
    arrays = [s["h_win"].astype(np.int32), s["s_win"].astype(np.int32),
              r_y, r_sign, s["valid"]]
    return dispatch_sharded("ed25519", ks, key_idx, arrays, len(items))
