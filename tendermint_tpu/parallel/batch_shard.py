"""Multi-chip sharding of the batch-verify + tally kernel.

The reference's parallelism analogue (SURVEY.md section 2.3): inside one
validator process, the signature batch for a commit is data-parallel over the
validator axis. We shard that axis across TPU devices with shard_map over a
1-D ("dp",) mesh; the per-device pass/fail bitmaps stay sharded and the
voting-power tally is all-reduced over ICI with psum - the on-device analogue
of the reference's libs/bits.BitArray + talliedVotingPower loop
(types/validator_set.go:685-714).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops import ed25519_batch

try:
    _shard_map = jax.shard_map  # jax >= 0.5
except AttributeError:  # older jax ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def make_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), ("dp",))


def _local_verify_tally(tab, h_win, s_win, r_y, r_sign, valid, power, for_block):
    ok = ed25519_batch._verify_kernel(
        tab, h_win, s_win, r_y, r_sign, valid, axis_name="dp"
    )
    # Tally voting power of passing, block-committing signatures; psum over
    # the device mesh so every chip holds the global tally.
    local = jnp.sum(jnp.where(ok & for_block, power, 0))
    tally = jax.lax.psum(local, "dp")
    all_ok = jax.lax.psum(jnp.sum(~ok & valid), "dp") == 0
    return ok, tally, all_ok


def sharded_verify_tally(mesh: Mesh):
    """Build the jitted multi-chip verify+tally step for `mesh`.

    Inputs are sharded on the signature axis; outputs: (bitmap (N,) sharded,
    global tally scalar, global all-valid-passed scalar)."""
    spec = P("dp")
    fn = _shard_map(
        _local_verify_tally,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec, spec),
        out_specs=(spec, P(), P()),
    )
    return jax.jit(fn)


def shard_args(mesh: Mesh, args: dict, power, for_block):
    """Device-put prepared numpy args with the dp sharding layout."""
    spec = NamedSharding(mesh, P("dp"))
    out = {k: jax.device_put(v, spec) for k, v in args.items()}
    out["power"] = jax.device_put(power, spec)
    out["for_block"] = jax.device_put(for_block, spec)
    return out


# ---------------------------------------------------------------------------
# Production path: Ed25519BatchVerifier routes here when >1 device
# ---------------------------------------------------------------------------

_mesh_cache: tuple[tuple, Mesh] | None = None
_fn_cache: dict[tuple, object] = {}


def _get_mesh() -> Mesh:
    global _mesh_cache
    devs = tuple(jax.devices())
    if _mesh_cache is None or _mesh_cache[0] != devs:
        _mesh_cache = (devs, make_mesh(list(devs)))
    return _mesh_cache[1]


def _local_verify(tab_full, idx, h_win, s_win, r_y, r_sign, valid):
    """Per-device body: gather this shard's comb tables from the replicated
    key-set table, then run the verify kernel. Gathering INSIDE shard_map
    keeps the per-call H2D payload to indices + scalars; the (heavy,
    height-persistent) tables replicate once per validator set."""
    tab = jnp.take(tab_full, idx, axis=0)
    return ed25519_batch._verify_kernel(
        tab, h_win, s_win, r_y, r_sign, valid, axis_name="dp")


def _sharded_verify_fn(mesh: Mesh):
    key = tuple(id(d) for d in mesh.devices.flat)
    fn = _fn_cache.get(key)
    if fn is None:
        fn = jax.jit(_shard_map(
            _local_verify,
            mesh=mesh,
            in_specs=(P(), P("dp"), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=P("dp"),
        ))
        _fn_cache[key] = fn
        if len(_fn_cache) > 4:
            _fn_cache.pop(next(iter(_fn_cache)))
    return fn


def replicated_tables(ks, mesh: Mesh):
    """The key set's comb tables replicated across the mesh, cached on the
    KeySet (validator sets persist across heights; replication is one-time)."""
    cached = ks.replicated
    key = tuple(id(d) for d in mesh.devices.flat)
    if cached is not None and cached[0] == key:
        return cached[1]
    tab = jax.device_put(ks.tab_ext, NamedSharding(mesh, P()))
    ks.replicated = (key, tab)
    return tab


def dispatch_batch_sharded(ks, key_idx, items, pub_ok):
    """Multi-device production dispatch: the signature axis shards over the
    ("dp",) mesh (the north-star sentence: validator sets sharded across TPU
    cores, pass/fail bitmap all-reduced). Dispatches in fixed
    n_devices*JNP_TILE chunks so no batch size triggers a fresh compile.

    Returns the (Npad,) bool device array without fetching (callers batch
    the readback); the bitmap is byte-identical to the single-device path."""
    import numpy as np

    mesh = _get_mesh()
    ndev = mesh.devices.size
    tile = ed25519_batch.JNP_TILE
    chunk = ndev * tile
    n = len(items)

    s = ed25519_batch.prepare_scalars(items, pub_ok, windows=True)
    r_y, r_sign = ed25519_batch._r_to_limbs(s["r32"])
    nb = -(-n // chunk) * chunk

    def pad(v, dtype=None):
        out = np.zeros((nb,) + v.shape[1:], dtype=dtype or v.dtype)
        out[:n] = v
        return out

    h_win = pad(s["h_win"].astype(np.int32))
    s_win = pad(s["s_win"].astype(np.int32))
    r_yp, r_sp = pad(r_y), pad(r_sign)
    valid = pad(s["valid"])
    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx

    tab_full = replicated_tables(ks, mesh)
    fn = _sharded_verify_fn(mesh)
    spec = NamedSharding(mesh, P("dp"))
    outs = []
    for off in range(0, nb, chunk):
        sl = slice(off, off + chunk)
        outs.append(fn(
            tab_full,
            jax.device_put(idx[sl], spec),
            jax.device_put(h_win[sl], spec),
            jax.device_put(s_win[sl], spec),
            jax.device_put(r_yp[sl], spec),
            jax.device_put(r_sp[sl], spec),
            jax.device_put(valid[sl], spec),
        ))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)
