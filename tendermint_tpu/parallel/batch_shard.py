"""Multi-chip sharding of the batch-verify + tally kernel.

The reference's parallelism analogue (SURVEY.md section 2.3): inside one
validator process, the signature batch for a commit is data-parallel over the
validator axis. We shard that axis across TPU devices with shard_map over a
1-D ("dp",) mesh; the per-device pass/fail bitmaps stay sharded and the
voting-power tally is all-reduced over ICI with psum - the on-device analogue
of the reference's libs/bits.BitArray + talliedVotingPower loop
(types/validator_set.go:685-714).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tendermint_tpu.ops import ed25519_batch


def make_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), ("dp",))


def _local_verify_tally(tab, h_win, s_win, r_y, r_sign, valid, power, for_block):
    ok = ed25519_batch._verify_kernel(
        tab, h_win, s_win, r_y, r_sign, valid, axis_name="dp"
    )
    # Tally voting power of passing, block-committing signatures; psum over
    # the device mesh so every chip holds the global tally.
    local = jnp.sum(jnp.where(ok & for_block, power, 0))
    tally = jax.lax.psum(local, "dp")
    all_ok = jax.lax.psum(jnp.sum(~ok & valid), "dp") == 0
    return ok, tally, all_ok


def sharded_verify_tally(mesh: Mesh):
    """Build the jitted multi-chip verify+tally step for `mesh`.

    Inputs are sharded on the signature axis; outputs: (bitmap (N,) sharded,
    global tally scalar, global all-valid-passed scalar)."""
    spec = P("dp")
    fn = jax.shard_map(
        _local_verify_tally,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec, spec),
        out_specs=(spec, P(), P()),
    )
    return jax.jit(fn)


def shard_args(mesh: Mesh, args: dict, power, for_block):
    """Device-put prepared numpy args with the dp sharding layout."""
    spec = NamedSharding(mesh, P("dp"))
    out = {k: jax.device_put(v, spec) for k, v in args.items()}
    out["power"] = jax.device_put(power, spec)
    out["for_block"] = jax.device_put(for_block, spec)
    return out
