"""Mempool reactor: tx gossip (reference: mempool/v0/reactor.go, channel 0x30,
proto/tendermint/mempool/types.proto Message{Txs}).

Each peer gets a gossip thread walking the mempool in insertion order (the
reference's clist walk), skipping txs the peer already sent us."""

from __future__ import annotations

import threading
import time

from tendermint_tpu.encoding import proto
from tendermint_tpu.mempool.mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    MempoolError,
)
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30
PEER_CATCHUP_SLEEP_S = 0.1


def msg_txs(txs: list[bytes]) -> bytes:
    inner = proto.Writer()
    for t in txs:
        inner.bytes(1, t)
    return proto.Writer().message(1, inner.out(), always=True).out()


class MempoolReactor(Reactor):
    def __init__(self, mempool: Mempool, broadcast: bool = True):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast_txs = broadcast
        self._peer_running: dict[str, bool] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast_txs:
            return
        self._peer_running[peer.id] = True
        threading.Thread(target=self._gossip_routine, args=(peer,), daemon=True).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        self._peer_running.pop(peer.id, None)

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 not in f:
            return
        inner = proto.fields(f[1][-1])
        for tx in inner.get(1, []):
            try:
                res = self.mempool.check_tx(tx, sender_peer=peer.id)
            except ErrTxInCache:
                pass  # gossip re-delivery: expected, never scored
            except ErrTxTooLarge:
                self._score(peer, "tx_too_large")
            except ErrMempoolIsFull:
                # full-pool rejects score LIGHTLY: an honest peer gossiping
                # into a saturated node is normal, a flood of these from
                # one peer is not (docs/OVERLOAD.md)
                self._score(peer, "mempool_full")
            except MempoolError:
                self._score(peer, "checktx_reject")
            except Exception:  # noqa: BLE001
                # an unexpected app/post-check blow-up must never kill the
                # recv thread — and it is OUR failure, not the peer's:
                # scoring it would ban every honest gossiper during an
                # ABCI app outage
                pass
            else:
                if not res.is_ok():
                    self._score(peer, "checktx_reject")

    def _score(self, peer: Peer, offense: str) -> None:
        sw = self.switch
        board = getattr(sw, "scoreboard", None) if sw is not None else None
        if board is not None:
            board.record(peer.id, offense)

    def _gossip_routine(self, peer: Peer) -> None:
        """One-tx-at-a-time walk (reference: mempool/v0/reactor.go
        broadcastTxRoutine)."""
        sent_seq = 0
        try:
            while self._peer_running.get(peer.id) and self.switch is not None:
                entries = self.mempool.iter_txs()
                progressed = False
                for m in entries:
                    if m.seq <= sent_seq:
                        continue
                    if peer.id in m.senders:
                        sent_seq = m.seq
                        progressed = True
                        continue
                    # don't send txs for future heights the peer can't process yet
                    if peer.try_send(MEMPOOL_CHANNEL, msg_txs([m.tx])):
                        sent_seq = m.seq
                        progressed = True
                    break
                if not progressed:
                    time.sleep(PEER_CATCHUP_SLEEP_S)
        except Exception as e:  # noqa: BLE001 - gossip ends like a
            # disconnect (peer teardown mid-send); a fresh routine starts
            # on re-add — but say so: a systematic bug here would
            # otherwise stop tx gossip cluster-wide with no trail
            logger = getattr(self.switch, "logger", None)
            if logger:
                logger.error("mempool gossip routine ended", peer=peer.id,
                             err=e)
