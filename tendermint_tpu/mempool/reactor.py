"""Mempool reactor: tx gossip (reference: mempool/v0/reactor.go, channel 0x30,
proto/tendermint/mempool/types.proto Message{Txs}).

Each peer gets a gossip thread walking the mempool in insertion order (the
reference's clist walk), skipping txs the peer already sent us. Two batching
upgrades over the reference (docs/INGEST.md):

 * RECEIVE: a multi-tx message is admitted through the micro-batched front
   door (``Mempool.ingest_txs`` -> ``check_tx_batch``) instead of a serial
   per-tx CheckTx loop — one mempool lock acquisition and one batched ABCI
   round trip per message (shared with concurrent RPC submissions via the
   ingest coalescer). The per-error peer-scoring table is IDENTICAL to the
   serial loop's (regression-gated in tests/test_ingest.py).
 * SEND: the gossip routine drains ALL currently-eligible txs for a peer
   into one wire message per tick (the ``Txs`` proto already encodes a
   repeated field), instead of the reference's one-tx-per-message walk.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.encoding import proto
from tendermint_tpu.mempool.mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    MempoolError,
)
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor

MEMPOOL_CHANNEL = 0x30
PEER_CATCHUP_SLEEP_S = 0.1
# Byte cap of one drained gossip message (well under the 10 MiB MConnection
# MAX_MSG_SIZE; keeps a deep mempool from head-of-line-blocking the channel)
GOSSIP_DRAIN_MAX_BYTES = 64 * 1024


def msg_txs(txs: list[bytes]) -> bytes:
    inner = proto.Writer()
    for t in txs:
        inner.bytes(1, t)
    return proto.Writer().message(1, inner.out(), always=True).out()


class MempoolReactor(Reactor):
    def __init__(self, mempool: Mempool, broadcast: bool = True):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast_txs = broadcast
        self._peer_running: dict[str, bool] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=5)]

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast_txs:
            return
        self._peer_running[peer.id] = True
        threading.Thread(target=self._gossip_routine, args=(peer,), daemon=True).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        self._peer_running.pop(peer.id, None)

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 not in f:
            return
        inner = proto.fields(f[1][-1])
        txs = list(inner.get(1, []))
        if not txs:
            return
        try:
            outcomes = self.mempool.ingest_txs(txs, sender_peer=peer.id)
        except Exception:  # noqa: BLE001 - an ingest-plumbing blow-up must
            # never kill the recv thread, and it is OUR failure, not the
            # peer's (scoring it would ban honest gossipers)
            return
        for o in outcomes:
            self._score_outcome(peer, o)

    def _score_outcome(self, peer: Peer, outcome) -> None:
        """The per-error scoring table — one place, applied identically to
        batched and serial admission outcomes (tests/test_ingest.py pins
        batched == serial attribution)."""
        if isinstance(outcome, ErrTxInCache):
            return  # gossip re-delivery: expected, never scored
        if isinstance(outcome, ErrTxTooLarge):
            self._score(peer, "tx_too_large")
            return
        if isinstance(outcome, ErrMempoolIsFull):
            # full-pool rejects score LIGHTLY: an honest peer gossiping
            # into a saturated node is normal, a flood of these from
            # one peer is not (docs/OVERLOAD.md)
            self._score(peer, "mempool_full")
            return
        if isinstance(outcome, MempoolError):
            self._score(peer, "checktx_reject")
            return
        if isinstance(outcome, Exception):
            # an unexpected app/post-check blow-up is OUR failure, not the
            # peer's: scoring it would ban every honest gossiper during an
            # ABCI app outage
            return
        if not outcome.is_ok():
            self._score(peer, "checktx_reject")

    def _score(self, peer: Peer, offense: str) -> None:
        sw = self.switch
        board = getattr(sw, "scoreboard", None) if sw is not None else None
        if board is not None:
            board.record(peer.id, offense)

    def _eligible_batch(self, peer: Peer, sent_seq: int):
        """Drain every currently-eligible tx for this peer (byte-capped)
        into one batch. Returns (batch, sent_seq, last_seq, progressed):
        ``sent_seq`` advances through a leading run of txs the peer
        already knows (safe even if the send fails — there is nothing
        pending before them); ``last_seq`` is where the cursor lands if
        the whole batch sends."""
        batch: list[bytes] = []
        batch_bytes = 0
        progressed = False
        last_seq = sent_seq
        for m in self.mempool.iter_txs():
            if m.seq <= sent_seq:
                continue
            if peer.id in m.senders:
                if not batch:
                    sent_seq = m.seq
                    progressed = True
                else:
                    last_seq = m.seq
                continue
            if batch and batch_bytes + len(m.tx) > GOSSIP_DRAIN_MAX_BYTES:
                break
            batch.append(m.tx)
            batch_bytes += len(m.tx)
            last_seq = m.seq
        return batch, sent_seq, last_seq, progressed

    def _gossip_routine(self, peer: Peer) -> None:
        """Drain-and-coalesce walk: all eligible txs per tick go out as ONE
        message (the reference's broadcastTxRoutine sends one tx each,
        mempool/v0/reactor.go)."""
        sent_seq = 0
        try:
            while self._peer_running.get(peer.id) and self.switch is not None:
                batch, sent_seq, last_seq, progressed = self._eligible_batch(
                    peer, sent_seq)
                if batch and peer.try_send(MEMPOOL_CHANNEL, msg_txs(batch)):
                    sent_seq = last_seq
                    progressed = True
                if not progressed:
                    time.sleep(PEER_CATCHUP_SLEEP_S)
        except Exception as e:  # noqa: BLE001 - gossip ends like a
            # disconnect (peer teardown mid-send); a fresh routine starts
            # on re-add — but say so: a systematic bug here would
            # otherwise stop tx gossip cluster-wide with no trail
            logger = getattr(self.switch, "logger", None)
            if logger:
                logger.error("mempool gossip routine ended", peer=peer.id,
                             err=e)
