"""Mempool: v0 FIFO clist semantics + v1 priority ordering (reference:
mempool/v0/clist_mempool.go:203,372,641, mempool/v1/mempool.go,
mempool/cache.go).

One implementation covers both reference versions behind Config.version:
"v0" reaps in insertion order; "v1" reaps by (priority desc, insertion asc)
using the ABCI CheckTx `priority` field. Gossip iteration (iter_txs) is
always insertion-ordered, mirroring the clist walk the reactors do.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field as dc_field

from tendermint_tpu.abci import types as abci
from tendermint_tpu.types.tx import tx_key


class MempoolError(Exception):
    pass


class ErrTxInCache(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


class ErrMempoolIsFull(MempoolError):
    def __init__(self, n, max_n, nbytes, max_bytes):
        super().__init__(
            f"mempool is full: number of txs {n} (max: {max_n}), total txs bytes {nbytes} (max: {max_bytes})"
        )


class ErrTxTooLarge(MempoolError):
    def __init__(self, max_size, size):
        super().__init__(f"Tx too large. Max size is {max_size}, but got {size}")


class ErrPreCheck(MempoolError):
    pass


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height at which tx entered the pool
    gas_wanted: int = 0
    priority: int = 0
    sender: str = ""
    seq: int = 0
    senders: set = dc_field(default_factory=set)  # peer ids that sent it
    time: float = 0.0  # wall clock at entry (TTL eviction)


class TxCache:
    """LRU dedup cache (reference: mempool/cache.go)."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        k = tx_key(tx)
        with self._mtx:
            if k in self._map:
                self._map.move_to_end(k)
                return False
            self._map[k] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key(tx), None)

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


class Mempool:
    def __init__(self, app, *, version: str = "v0", max_txs: int = 5000,
                 max_txs_bytes: int = 1024 * 1024 * 1024,
                 cache_size: int = 10000, max_tx_bytes: int = 1024 * 1024,
                 keep_invalid_txs_in_cache: bool = False,
                 recheck: bool = True,
                 ttl_duration_s: float = 0.0, ttl_num_blocks: int = 0):
        self.app = app  # proxy.AppConnMempool-like
        self.version = version
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.max_tx_bytes = max_tx_bytes
        self.keep_invalid = keep_invalid_txs_in_cache
        self.recheck = recheck
        # 0 disables each bound (reference: mempool/v1/mempool.go
        # purgeExpiredTxs; config.toml ttl-duration / ttl-num-blocks)
        self.ttl_duration_s = ttl_duration_s
        self.ttl_num_blocks = ttl_num_blocks

        self.cache = TxCache(cache_size)
        self._txs: OrderedDict[bytes, MempoolTx] = OrderedDict()  # key -> tx
        self._txs_bytes = 0
        self._height = 0
        self._seq = 0
        self._mtx = threading.RLock()
        self._notified_available = False
        self._txs_available: threading.Event | None = None
        self.pre_check = None   # fn(tx) -> raises ErrPreCheck
        self.post_check = None  # fn(tx, res) -> raises
        # flight recorder (utils/trace.py): node wiring installs the node's
        # tracer; None = untraced (standalone mempools, tests)
        self.tracer = None

    # --- Mempool interface (reference: mempool/mempool.go:14-90) -----------

    def size(self) -> int:
        with self._mtx:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._mtx:
            return self._txs_bytes

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> threading.Event | None:
        return self._txs_available

    def check_tx(self, tx: bytes, sender_peer: str = "") -> abci.ResponseCheckTx:
        """Synchronous CheckTx (reference: mempool/v0/clist_mempool.go:203)."""
        if len(tx) > self.max_tx_bytes:
            raise ErrTxTooLarge(self.max_tx_bytes, len(tx))
        if self.pre_check is not None:
            self.pre_check(tx)
        with self._mtx:
            full = (len(self._txs) >= self.max_txs
                    or self._txs_bytes + len(tx) > self.max_txs_bytes)
            if full and self.version != "v1":
                # v0 rejects when full; v1 may evict lower-priority txs
                # AFTER the app has priced the newcomer (see below).
                raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                       self._txs_bytes, self.max_txs_bytes)
        if not self.cache.push(tx):
            # record extra sender for gossip suppression
            with self._mtx:
                existing = self._txs.get(tx_key(tx))
                if existing is not None and sender_peer:
                    existing.senders.add(sender_peer)
            raise ErrTxInCache()

        tr = self.tracer
        if tr is not None and tr.enabled:
            with tr.span("mempool.check_tx", bytes=len(tx)):
                res = self.app.check_tx(
                    abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        else:
            res = self.app.check_tx(
                abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        if self.post_check is not None:
            try:
                self.post_check(tx, res)
            except Exception:
                # post-check failure = invalid tx (reference resCbFirstTime):
                # it must not stay cached unless keep_invalid says so
                if not self.keep_invalid:
                    self.cache.remove(tx)
                raise
        if res.is_ok():
            with self._mtx:
                self._make_room_locked(tx, res.priority)
                self._seq += 1
                mtx = MempoolTx(tx=tx, height=self._height,
                                gas_wanted=res.gas_wanted, priority=res.priority,
                                sender=res.sender, seq=self._seq,
                                time=time.monotonic())
                if sender_peer:
                    mtx.senders.add(sender_peer)
                self._txs[tx_key(tx)] = mtx
                self._txs_bytes += len(tx)
                self._notify_txs_available()
        else:
            if not self.keep_invalid:
                self.cache.remove(tx)
        return res

    def _make_room_locked(self, tx: bytes, priority: int) -> None:
        """v1 full-pool admission (reference: mempool/v1/mempool.go:505-577):
        evict strictly-lower-priority txs, lowest first (ties: newest
        first), until the newcomer fits; if the eligible victims can't make
        enough room, reject it — and drop it from the dedup cache so a
        later retry isn't refused as a duplicate."""
        need_count = 1 if len(self._txs) >= self.max_txs else 0
        need_bytes = max(0, self._txs_bytes + len(tx) - self.max_txs_bytes)
        if not need_count and not need_bytes:
            return
        if self.version != "v1":
            # v0 reached here only via a fill-up race between the unlocked
            # pre-check and insertion: reject-when-full, never evict.
            self.cache.remove(tx)
            raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                   self._txs_bytes, self.max_txs_bytes)
        victims = [m for m in self._txs.values() if m.priority < priority]
        # Feasibility mirrors the reference exactly (mempool/v1/mempool.go
        # canAddTx caller): reject unless the victims' TOTAL size covers the
        # FULL size of the incoming tx — not merely the byte overflow
        # (round-4 advisor finding: the overflow comparison admitted txs in
        # near-full edge cases the reference rejects).
        if not victims or sum(len(v.tx) for v in victims) < len(tx):
            self.cache.remove(tx)
            raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                   self._txs_bytes, self.max_txs_bytes)
        victims.sort(key=lambda m: (m.priority, -m.seq))
        freed_bytes = freed_count = 0
        for v in victims:
            del self._txs[tx_key(v.tx)]
            self._txs_bytes -= len(v.tx)
            self.cache.remove(v.tx)
            freed_bytes += len(v.tx)
            freed_count += 1
            if freed_bytes >= need_bytes and freed_count >= need_count:
                break

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """reference: mempool/v0/clist_mempool.go:519-555; v1 orders by
        priority."""
        from tendermint_tpu.encoding.proto import encode_uvarint

        with self._mtx:
            entries = list(self._txs.values())
            if self.version == "v1":
                entries.sort(key=lambda m: (-m.priority, m.seq))
            out = []
            total_bytes = 0
            total_gas = 0
            for m in entries:
                aux = len(m.tx) + len(encode_uvarint(len(m.tx))) + 1
                if max_bytes > -1 and total_bytes + aux > max_bytes:
                    break
                if max_gas > -1 and total_gas + m.gas_wanted > max_gas:
                    break
                total_bytes += aux
                total_gas += m.gas_wanted
                out.append(m.tx)
            return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._mtx:
            entries = list(self._txs.values())
            if self.version == "v1":
                entries.sort(key=lambda m: (-m.priority, m.seq))
            if n < 0:
                n = len(entries)
            return [m.tx for m in entries[:n]]

    def update(self, height: int, txs: list[bytes],
               deliver_tx_responses: list[abci.ResponseDeliverTx] | None = None,
               pre_check=None, post_check=None) -> None:
        """Remove committed txs; recheck the rest (reference:
        mempool/v0/clist_mempool.go:577-639). Caller must hold the lock.
        pre_check/post_check, when given, replace the admission filters —
        they derive from the NEW state (state/tx_filter.py)."""
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        self._height = height
        self._notified_available = False
        for i, tx in enumerate(txs):
            ok = deliver_tx_responses is None or deliver_tx_responses[i].is_ok()
            if ok:
                self.cache.push(tx)  # committed: keep in cache to reject re-adds
            elif not self.keep_invalid:
                self.cache.remove(tx)
            k = tx_key(tx)
            m = self._txs.pop(k, None)
            if m is not None:
                self._txs_bytes -= len(m.tx)
        self._purge_expired(height)
        if self.recheck and self._txs:
            self._recheck_txs()
        if self._txs:
            self._notify_txs_available()

    def _purge_expired(self, height: int) -> None:
        """Evict txs past their TTL (reference: mempool/v1/mempool.go
        purgeExpiredTxs): ttl_num_blocks bounds blocks-in-pool,
        ttl_duration_s bounds wall-clock age; either at 0 is disabled.
        Expired txs leave the cache too, so a later resubmission is not
        rejected as a duplicate. Caller must hold the lock."""
        if not self.ttl_num_blocks and not self.ttl_duration_s:
            return
        now = time.monotonic()
        for k in list(self._txs.keys()):
            m = self._txs[k]
            expired = (
                (self.ttl_num_blocks > 0
                 and height - m.height > self.ttl_num_blocks)
                or (self.ttl_duration_s > 0
                    and now - m.time > self.ttl_duration_s))
            if expired:
                del self._txs[k]
                self._txs_bytes -= len(m.tx)
                self.cache.remove(m.tx)

    def _recheck_txs(self) -> None:
        """reference: mempool/v0/clist_mempool.go:641-664; the post-check
        filter applies on recheck too (resCbRecheck -> postCheck), so a
        max_gas tightened by the applied block evicts over-priced txs."""
        for k in list(self._txs.keys()):
            m = self._txs[k]
            res = self.app.check_tx(
                abci.RequestCheckTx(tx=m.tx, type=abci.CHECK_TX_TYPE_RECHECK)
            )
            ok = res.is_ok()
            if ok and self.post_check is not None:
                try:
                    self.post_check(m.tx, res)
                except Exception:  # noqa: BLE001 - filter verdict, not error
                    ok = False
            if not ok:
                del self._txs[k]
                self._txs_bytes -= len(m.tx)
                if not self.keep_invalid:
                    self.cache.remove(m.tx)

    def flush(self) -> None:
        with self._mtx:
            self._txs.clear()
            self._txs_bytes = 0
            self.cache.reset()

    def remove_tx_by_key(self, key: bytes) -> None:
        with self._mtx:
            m = self._txs.pop(key, None)
            if m is not None:
                self._txs_bytes -= len(m.tx)
                self.cache.remove(m.tx)

    def iter_txs(self) -> list[MempoolTx]:
        """Insertion-ordered snapshot for gossip (the clist walk)."""
        with self._mtx:
            return list(self._txs.values())

    def _notify_txs_available(self) -> None:
        if self._txs_available is not None and not self._notified_available:
            self._notified_available = True
            self._txs_available.set()
