"""Mempool: v0 FIFO clist semantics + v1 priority ordering (reference:
mempool/v0/clist_mempool.go:203,372,641, mempool/v1/mempool.go,
mempool/cache.go).

One implementation covers both reference versions behind Config.version:
"v0" reaps in insertion order; "v1" reaps by (priority desc, insertion asc)
using the ABCI CheckTx `priority` field. Gossip iteration (iter_txs) is
always insertion-ordered, mirroring the clist walk the reactors do.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field as dc_field

from tendermint_tpu.abci import types as abci
from tendermint_tpu.mempool.ingest import IngestCoalescer
from tendermint_tpu.mempool import ingest as _ingest
from tendermint_tpu.types.tx import tx_key
from tendermint_tpu.utils import faults


class MempoolError(Exception):
    pass


class ErrTxInCache(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


class ErrMempoolIsFull(MempoolError):
    def __init__(self, n, max_n, nbytes, max_bytes):
        super().__init__(
            f"mempool is full: number of txs {n} (max: {max_n}), total txs bytes {nbytes} (max: {max_bytes})"
        )


class ErrTxTooLarge(MempoolError):
    def __init__(self, max_size, size):
        super().__init__(f"Tx too large. Max size is {max_size}, but got {size}")


class ErrPreCheck(MempoolError):
    pass


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height at which tx entered the pool
    gas_wanted: int = 0
    priority: int = 0
    sender: str = ""
    seq: int = 0
    senders: set = dc_field(default_factory=set)  # peer ids that sent it
    time: float = 0.0  # wall clock at entry (TTL eviction)


class TxCache:
    """LRU dedup cache (reference: mempool/cache.go)."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present."""
        k = tx_key(tx)
        with self._mtx:
            if k in self._map:
                self._map.move_to_end(k)
                return False
            self._map[k] = None
            if len(self._map) > self.size:
                self._map.popitem(last=False)
            return True

    def contains(self, tx: bytes) -> bool:
        """Peek without the LRU bump (the batch pre-filter's dedup probe;
        the authoritative push happens at the replay's serial position)."""
        with self._mtx:
            return tx_key(tx) in self._map

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key(tx), None)

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


class Mempool:
    def __init__(self, app, *, version: str = "v0", max_txs: int = 5000,
                 max_txs_bytes: int = 1024 * 1024 * 1024,
                 cache_size: int = 10000, max_tx_bytes: int = 1024 * 1024,
                 keep_invalid_txs_in_cache: bool = False,
                 recheck: bool = True,
                 ttl_duration_s: float = 0.0, ttl_num_blocks: int = 0):
        self.app = app  # proxy.AppConnMempool-like
        self.version = version
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.max_tx_bytes = max_tx_bytes
        self.keep_invalid = keep_invalid_txs_in_cache
        self.recheck = recheck
        # 0 disables each bound (reference: mempool/v1/mempool.go
        # purgeExpiredTxs; config.toml ttl-duration / ttl-num-blocks)
        self.ttl_duration_s = ttl_duration_s
        self.ttl_num_blocks = ttl_num_blocks

        self.cache = TxCache(cache_size)
        self._txs: OrderedDict[bytes, MempoolTx] = OrderedDict()  # key -> tx
        self._txs_bytes = 0
        self._height = 0
        self._seq = 0
        self._mtx = threading.RLock()
        self._notified_available = False
        self._txs_available: threading.Event | None = None
        self.pre_check = None   # fn(tx) -> raises ErrPreCheck
        self.post_check = None  # fn(tx, res) -> raises
        # flight recorder (utils/trace.py): node wiring installs the node's
        # tracer; None = untraced (standalone mempools, tests)
        self.tracer = None
        # the micro-batching front door (mempool/ingest.py): lazy executor,
        # costs nothing until the first ingest_tx/ingest_txs submission
        self._ingest = IngestCoalescer(self)

    # --- Mempool interface (reference: mempool/mempool.go:14-90) -----------

    def size(self) -> int:
        with self._mtx:
            return len(self._txs)

    def size_bytes(self) -> int:
        with self._mtx:
            return self._txs_bytes

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> threading.Event | None:
        return self._txs_available

    def check_tx(self, tx: bytes, sender_peer: str = "") -> abci.ResponseCheckTx:
        """Synchronous CheckTx (reference: mempool/v0/clist_mempool.go:203).

        INVARIANT: check_tx_batch's phase-2 replay below mirrors this
        decision procedure step for step; any semantic change here MUST be
        mirrored there (the batched path's bit-identical guarantee is
        differentially gated by tests/test_ingest.py and
        __graft_entry__.ingest_stage, which will fail loudly on drift)."""
        if len(tx) > self.max_tx_bytes:
            raise ErrTxTooLarge(self.max_tx_bytes, len(tx))
        if self.pre_check is not None:
            self.pre_check(tx)
        with self._mtx:
            full = (len(self._txs) >= self.max_txs
                    or self._txs_bytes + len(tx) > self.max_txs_bytes)
            if full and self.version != "v1":
                # v0 rejects when full; v1 may evict lower-priority txs
                # AFTER the app has priced the newcomer (see below).
                raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                       self._txs_bytes, self.max_txs_bytes)
        if not self.cache.push(tx):
            # record extra sender for gossip suppression
            with self._mtx:
                existing = self._txs.get(tx_key(tx))
                if existing is not None and sender_peer:
                    existing.senders.add(sender_peer)
            raise ErrTxInCache()

        tr = self.tracer
        if tr is not None and tr.enabled:
            with tr.span("mempool.check_tx", bytes=len(tx)):
                res = self.app.check_tx(
                    abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        else:
            res = self.app.check_tx(
                abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        if self.post_check is not None:
            try:
                self.post_check(tx, res)
            except Exception:
                # post-check failure = invalid tx (reference resCbFirstTime):
                # it must not stay cached unless keep_invalid says so
                if not self.keep_invalid:
                    self.cache.remove(tx)
                raise
        if res.is_ok():
            with self._mtx:
                self._make_room_locked(tx, res.priority)
                self._seq += 1
                mtx = MempoolTx(tx=tx, height=self._height,
                                gas_wanted=res.gas_wanted, priority=res.priority,
                                sender=res.sender, seq=self._seq,
                                time=time.monotonic())
                if sender_peer:
                    mtx.senders.add(sender_peer)
                self._txs[tx_key(tx)] = mtx
                self._txs_bytes += len(tx)
                self._notify_txs_available()
        else:
            if not self.keep_invalid:
                self.cache.remove(tx)
        return res

    # --- the micro-batched front door (mempool/ingest.py, docs/INGEST.md) --

    def ingest_tx(self, tx: bytes, sender_peer: str = "") -> abci.ResponseCheckTx:
        """The coalesced front door: same returns and same raises as
        check_tx, but concurrent callers (RPC handler threads, gossip recv
        threads) share batched CheckTx dispatches through the ingest
        coalescer. TMTPU_INGEST=0 restores the serial path verbatim."""
        if not _ingest.enabled():
            return self.check_tx(tx, sender_peer)
        p = self._ingest.submit(tx, sender_peer)
        tr = self.tracer
        if tr is not None and tr.enabled:
            t0 = time.monotonic()
            try:
                return p.wait()
            finally:
                tr.record("mempool.ingest_wait", time.monotonic() - t0)
        return p.wait()

    def ingest_txs(self, txs: list[bytes], sender_peer: str = "") -> list:
        """Multi-tx front door (gossip deliveries): per-tx outcomes —
        a ResponseCheckTx where the serial loop would return one, the
        exception instance where it would raise. Never raises itself."""
        if not _ingest.enabled():
            out = []
            for tx in txs:
                try:
                    out.append(self.check_tx(tx, sender_peer))
                except Exception as e:  # noqa: BLE001 - outcome, not error
                    out.append(e)
            return out
        pendings = [self._ingest.submit(tx, sender_peer) for tx in txs]
        for p in pendings:
            p.done.wait()
        return [p.outcome for p in pendings]

    def check_tx_batch(self, txs: list[bytes], senders: list[str] | None = None,
                       tx_type: int = abci.CHECK_TX_TYPE_NEW) -> list:
        """Admit a micro-batch through ONE batched ABCI CheckTx and ONE
        mempool lock acquisition (docs/INGEST.md).

        Returns a per-tx outcome list, order-aligned with ``txs``: a
        ResponseCheckTx where the serial check_tx would return one, the
        exact exception INSTANCE where it would raise. The decision
        procedure IS the serial loop's, replayed in original order under
        the lock — admission verdicts, v1 eviction, priority order, cache
        effects, and per-sender attribution are bit-identical to N serial
        calls; only the app round trip is batched. (A tx the replay later
        rejects as full may have been priced by the app anyway — CheckTx
        is stateless by ABCI contract, as in the reference's async
        mempool.) A failure of the batched dispatch itself — injected
        fault, transport error, a pre-batch remote app — degrades to the
        serial per-tx loop, so every caller still gets the serial path's
        exact outcome."""
        n = len(txs)
        if senders is None:
            senders = [""] * n
        out: list = [None] * n
        # --- phase 1: per-tx pre-verdicts + the app-batch candidate set ----
        # (size/pre_check verdicts are final; the cache probe only decides
        # who rides the batched dispatch — the authoritative push happens
        # at each tx's serial position in the replay below)
        need: list[int] = []
        seen: set[bytes] = set()
        for i, tx in enumerate(txs):
            if len(tx) > self.max_tx_bytes:
                out[i] = ErrTxTooLarge(self.max_tx_bytes, len(tx))
                continue
            if self.pre_check is not None:
                try:
                    self.pre_check(tx)
                except Exception as e:  # noqa: BLE001 - serial raises it
                    out[i] = e
                    continue
            k = tx_key(tx)
            if k in seen or self.cache.contains(tx):
                # expected duplicate: no app call; the replay confirms via
                # the real cache.push (and falls back to a serial app call
                # when the earlier copy was un-cached in the meantime)
                continue
            seen.add(k)
            need.append(i)
        # --- the batched app round trips (outside the mempool lock) --------
        responses: dict[int, object] = {}
        if need:
            batch = [txs[i] for i in need]
            try:
                faults.fire("mempool.ingest")
                tr = self.tracer
                if tr is not None and tr.enabled:
                    with tr.span("mempool.ingest_batch", n=len(batch)):
                        rs = self._batched_app_check(batch, tx_type)
                else:
                    rs = self._batched_app_check(batch, tx_type)
                for i, r in zip(need, rs):
                    responses[i] = r
            except Exception:  # noqa: BLE001 - degrade to the serial loop
                for i in need:
                    try:
                        responses[i] = self.app.check_tx(
                            abci.RequestCheckTx(tx=txs[i], type=tx_type))
                    except Exception as e:  # noqa: BLE001 - per-tx outcome
                        responses[i] = e
        # --- phase 2: serial-order replay under ONE lock acquisition -------
        # INVARIANT: this loop IS check_tx's decision procedure (see its
        # docstring) — keep the two in lockstep; the differential gates
        # (tests/test_ingest.py, __graft_entry__.ingest_stage) fail on drift.
        pushed: set[int] = set()
        i = 0
        while i < n:
            deferred = -1
            with self._mtx:
                while i < n:
                    if out[i] is not None:
                        i += 1
                        continue
                    tx = txs[i]
                    full = (len(self._txs) >= self.max_txs
                            or self._txs_bytes + len(tx) > self.max_txs_bytes)
                    if full and self.version != "v1":
                        # v0 rejects-when-full BEFORE the cache push, so a
                        # retry after commit is not refused as a duplicate
                        out[i] = ErrMempoolIsFull(
                            len(self._txs), self.max_txs,
                            self._txs_bytes, self.max_txs_bytes)
                        i += 1
                        continue
                    if i not in pushed:
                        if not self.cache.push(tx):
                            existing = self._txs.get(tx_key(tx))
                            if existing is not None and senders[i]:
                                existing.senders.add(senders[i])
                            out[i] = ErrTxInCache()
                            i += 1
                            continue
                        pushed.add(i)
                    res = responses.get(i)
                    if res is None:
                        # a duplicate whose earlier copy was un-cached
                        # before the replay reached it: the serial path
                        # would call the app HERE — do so outside the lock
                        deferred = i
                        break
                    if isinstance(res, Exception):
                        # serial semantics: an app blow-up propagates
                        # AFTER the cache push, with the tx left cached
                        out[i] = res
                        i += 1
                        continue
                    if self.post_check is not None:
                        try:
                            self.post_check(tx, res)
                        except Exception as e:  # noqa: BLE001 - verdict
                            if not self.keep_invalid:
                                self.cache.remove(tx)
                            out[i] = e
                            i += 1
                            continue
                    if res.is_ok():
                        try:
                            self._make_room_locked(tx, res.priority)
                        except MempoolError as e:
                            out[i] = e
                            i += 1
                            continue
                        self._seq += 1
                        mtx = MempoolTx(
                            tx=tx, height=self._height,
                            gas_wanted=res.gas_wanted, priority=res.priority,
                            sender=res.sender, seq=self._seq,
                            time=time.monotonic())
                        if senders[i]:
                            mtx.senders.add(senders[i])
                        self._txs[tx_key(tx)] = mtx
                        self._txs_bytes += len(tx)
                        self._notify_txs_available()
                    else:
                        if not self.keep_invalid:
                            self.cache.remove(tx)
                    out[i] = res
                    i += 1
            if deferred >= 0:
                try:
                    responses[deferred] = self.app.check_tx(
                        abci.RequestCheckTx(tx=txs[deferred], type=tx_type))
                except Exception as e:  # noqa: BLE001 - per-tx outcome
                    responses[deferred] = e
        self._observe_batch(n, out)
        return out

    # The ABCI wire caps one message at 100 MiB (abci/wire.py
    # MAX_MSG_SIZE); a front-door batch of max_tx_bytes-sized txs (or a
    # whole-pool recheck) must never be able to exceed it and kill the
    # mempool connection. Chunked well under the cap.
    BATCH_MAX_BYTES = 8 * 1024 * 1024

    def _batched_app_check(self, txs: list[bytes], tx_type: int) -> list:
        """One or more RequestCheckTxBatch round trips, chunked under
        BATCH_MAX_BYTES. Returns responses order-aligned with ``txs``;
        raises (to the caller's serial fallback) on a response-shape
        mismatch or transport failure."""
        out: list = []
        start = 0
        n = len(txs)
        while start < n:
            nbytes = 0
            end = start
            while end < n and (end == start
                               or nbytes + len(txs[end]) <= self.BATCH_MAX_BYTES):
                nbytes += len(txs[end])
                end += 1
            chunk = txs[start:end]
            resp = self.app.check_tx_batch(
                abci.RequestCheckTxBatch(txs=chunk, type=tx_type))
            if len(resp.responses) != len(chunk):
                raise MempoolError(
                    f"CheckTxBatch returned {len(resp.responses)} responses "
                    f"for {len(chunk)} txs")
            out.extend(resp.responses)
            start = end
        return out

    def _observe_batch(self, n: int, out: list) -> None:
        """Pre-seeded ingest metrics (utils/metrics.py, tmlint
        metrics-discipline); counters must never be able to fail a batch."""
        try:
            from tendermint_tpu.utils import metrics as tmmetrics

            m = tmmetrics.GLOBAL_NODE_METRICS
            if m is None:
                return
            m.ingest_batch_size.observe(n)
            ok = sum(1 for o in out
                     if not isinstance(o, Exception) and o.is_ok())
            m.ingest_txs.add(ok, result="ok")
            m.ingest_txs.add(n - ok, result="reject")
        except Exception:  # noqa: BLE001 - observability never blocks txs
            pass

    def _make_room_locked(self, tx: bytes, priority: int) -> None:
        """v1 full-pool admission (reference: mempool/v1/mempool.go:505-577):
        evict strictly-lower-priority txs, lowest first (ties: newest
        first), until the newcomer fits; if the eligible victims can't make
        enough room, reject it — and drop it from the dedup cache so a
        later retry isn't refused as a duplicate."""
        need_count = 1 if len(self._txs) >= self.max_txs else 0
        need_bytes = max(0, self._txs_bytes + len(tx) - self.max_txs_bytes)
        if not need_count and not need_bytes:
            return
        if self.version != "v1":
            # v0 reached here only via a fill-up race between the unlocked
            # pre-check and insertion: reject-when-full, never evict.
            self.cache.remove(tx)
            raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                   self._txs_bytes, self.max_txs_bytes)
        victims = [m for m in self._txs.values() if m.priority < priority]
        # Feasibility mirrors the reference exactly (mempool/v1/mempool.go
        # canAddTx caller): reject unless the victims' TOTAL size covers the
        # FULL size of the incoming tx — not merely the byte overflow
        # (round-4 advisor finding: the overflow comparison admitted txs in
        # near-full edge cases the reference rejects).
        if not victims or sum(len(v.tx) for v in victims) < len(tx):
            self.cache.remove(tx)
            raise ErrMempoolIsFull(len(self._txs), self.max_txs,
                                   self._txs_bytes, self.max_txs_bytes)
        victims.sort(key=lambda m: (m.priority, -m.seq))
        freed_bytes = freed_count = 0
        for v in victims:
            del self._txs[tx_key(v.tx)]
            self._txs_bytes -= len(v.tx)
            self.cache.remove(v.tx)
            freed_bytes += len(v.tx)
            freed_count += 1
            if freed_bytes >= need_bytes and freed_count >= need_count:
                break

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """reference: mempool/v0/clist_mempool.go:519-555; v1 orders by
        priority."""
        from tendermint_tpu.encoding.proto import encode_uvarint

        with self._mtx:
            entries = list(self._txs.values())
            if self.version == "v1":
                entries.sort(key=lambda m: (-m.priority, m.seq))
            out = []
            total_bytes = 0
            total_gas = 0
            for m in entries:
                aux = len(m.tx) + len(encode_uvarint(len(m.tx))) + 1
                if max_bytes > -1 and total_bytes + aux > max_bytes:
                    break
                if max_gas > -1 and total_gas + m.gas_wanted > max_gas:
                    break
                total_bytes += aux
                total_gas += m.gas_wanted
                out.append(m.tx)
            return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._mtx:
            entries = list(self._txs.values())
            if self.version == "v1":
                entries.sort(key=lambda m: (-m.priority, m.seq))
            if n < 0:
                n = len(entries)
            return [m.tx for m in entries[:n]]

    def update(self, height: int, txs: list[bytes],
               deliver_tx_responses: list[abci.ResponseDeliverTx] | None = None,
               pre_check=None, post_check=None) -> None:
        """Remove committed txs; recheck the rest (reference:
        mempool/v0/clist_mempool.go:577-639). Caller must hold the lock.
        pre_check/post_check, when given, replace the admission filters —
        they derive from the NEW state (state/tx_filter.py)."""
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        self._height = height
        self._notified_available = False
        for i, tx in enumerate(txs):
            ok = deliver_tx_responses is None or deliver_tx_responses[i].is_ok()
            if ok:
                self.cache.push(tx)  # committed: keep in cache to reject re-adds
            elif not self.keep_invalid:
                self.cache.remove(tx)
            k = tx_key(tx)
            m = self._txs.pop(k, None)
            if m is not None:
                self._txs_bytes -= len(m.tx)
        self._purge_expired(height)
        if self.recheck and self._txs:
            self._recheck_txs()
        if self._txs:
            self._notify_txs_available()

    def _purge_expired(self, height: int) -> None:
        """Evict txs past their TTL (reference: mempool/v1/mempool.go
        purgeExpiredTxs): ttl_num_blocks bounds blocks-in-pool,
        ttl_duration_s bounds wall-clock age; either at 0 is disabled.
        Expired txs leave the cache too, so a later resubmission is not
        rejected as a duplicate. Caller must hold the lock."""
        if not self.ttl_num_blocks and not self.ttl_duration_s:
            return
        now = time.monotonic()
        for k in list(self._txs.keys()):
            m = self._txs[k]
            expired = (
                (self.ttl_num_blocks > 0
                 and height - m.height > self.ttl_num_blocks)
                or (self.ttl_duration_s > 0
                    and now - m.time > self.ttl_duration_s))
            if expired:
                del self._txs[k]
                self._txs_bytes -= len(m.tx)
                self.cache.remove(m.tx)

    def _recheck_txs(self) -> None:
        """reference: mempool/v0/clist_mempool.go:641-664; the post-check
        filter applies on recheck too (resCbRecheck -> postCheck), so a
        max_gas tightened by the applied block evicts over-priced txs.

        The app round trips ride the batched CheckTx path (ONE
        RequestCheckTxBatch for the whole pool, docs/INGEST.md); the
        eviction replay below is unchanged, so recheck survivors are
        bit-identical to the serial loop. A batch-dispatch failure (or a
        pre-batch remote app) degrades to the per-tx loop."""
        keys = list(self._txs.keys())
        responses = None
        if len(keys) > 1 and getattr(self.app, "check_tx_batch", None) is not None:
            txs = [self._txs[k].tx for k in keys]
            try:
                faults.fire("mempool.ingest")
                responses = self._batched_app_check(
                    txs, abci.CHECK_TX_TYPE_RECHECK)
            except Exception:  # noqa: BLE001 - serial fallback below
                responses = None
        for idx, k in enumerate(keys):
            m = self._txs[k]
            if responses is not None:
                res = responses[idx]
            else:
                res = self.app.check_tx(abci.RequestCheckTx(
                    tx=m.tx, type=abci.CHECK_TX_TYPE_RECHECK))
            ok = res.is_ok()
            if ok and self.post_check is not None:
                try:
                    self.post_check(m.tx, res)
                except Exception:  # noqa: BLE001 - filter verdict, not error
                    ok = False
            if not ok:
                del self._txs[k]
                self._txs_bytes -= len(m.tx)
                if not self.keep_invalid:
                    self.cache.remove(m.tx)

    def flush(self) -> None:
        with self._mtx:
            self._txs.clear()
            self._txs_bytes = 0
            self.cache.reset()

    def remove_tx_by_key(self, key: bytes) -> None:
        with self._mtx:
            m = self._txs.pop(key, None)
            if m is not None:
                self._txs_bytes -= len(m.tx)
                self.cache.remove(m.tx)

    def iter_txs(self) -> list[MempoolTx]:
        """Insertion-ordered snapshot for gossip (the clist walk)."""
        with self._mtx:
            return list(self._txs.values())

    def _notify_txs_available(self) -> None:
        if self._txs_available is not None and not self._notified_available:
            self._notified_available = True
            self._txs_available.set()
