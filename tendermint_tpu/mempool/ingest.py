"""Micro-batched tx ingestion front door (ROADMAP item 2, docs/INGEST.md).

After PRs 2/4/11 coalesced all signature verification into shared kernel
launches, tx admission was the last decision-at-a-time path: every
``broadcast_tx_*`` and every gossiped tx paid its own ABCI CheckTx round
trip and its own mempool lock acquisition. This module applies the same
continuous-batching shape (crypto/verify_service.py) to ingestion:

 * concurrent front-door submissions — RPC ``broadcast_tx_*`` handler
   threads AND gossip ``MempoolReactor.receive`` deliveries — are queued
   to one per-mempool :class:`IngestCoalescer`;
 * a dedicated executor thread drains submissions arriving within a short
   window (``TMTPU_INGEST_WINDOW_US``) into one
   ``Mempool.check_tx_batch`` call: ONE mempool lock acquisition and ONE
   batched ABCI ``RequestCheckTxBatch`` dispatch per micro-batch, with
   per-tx outcomes scattered back to each waiter (the dispatch/resolve
   seam shape of crypto/batch.PendingVerify);
 * admission semantics are the SERIAL loop's, replayed in order inside
   ``check_tx_batch`` — identical verdicts, priority order, cache effects,
   and per-sender scoring attribution; only the app round trip amortizes;
 * the RPC admission gate (rpc/core._TxAdmissionGate, docs/OVERLOAD.md)
   composes unchanged: each batch-member's handler thread holds its own
   slot for the life of its CheckTx, so shed behavior is identical while
   the CheckTx cost under the slots amortizes.

Knobs (docs/CONFIG.md): ``TMTPU_INGEST=0`` restores the serial per-tx
path; ``TMTPU_INGEST_WINDOW_US`` sets the coalescing window (default
200); ``TMTPU_INGEST_MAX_BATCH`` caps txs per shared batch (default 256).
"""

from __future__ import annotations

import os
import queue
import threading
import time as _time


def enabled() -> bool:
    """False only when the operator opted out (TMTPU_INGEST=0; read per
    submission so tests and the mempool_ingest bench can flip it without
    rebuilding mempools)."""
    return os.environ.get("TMTPU_INGEST") != "0"


def window_us(default: int = 200) -> int:
    """Coalescing window: how long the executor waits for more submissions
    after the first before dispatching the shared batch. Latency cost for
    a lone tx; the price of sharing the round trip for concurrent ones.
    TMTPU_INGEST_WINDOW_US overrides."""
    v = os.environ.get("TMTPU_INGEST_WINDOW_US")
    try:
        return max(0, int(v)) if v else default
    except ValueError:
        return default


def max_batch(default: int = 256) -> int:
    """Tx cap per shared batch (bounds one batch's lock-hold time and the
    app's worst-case batched CheckTx). TMTPU_INGEST_MAX_BATCH overrides."""
    v = os.environ.get("TMTPU_INGEST_MAX_BATCH")
    try:
        return max(1, int(v)) if v else default
    except ValueError:
        return default


class PendingCheckTx:
    """One caller's submitted tx: a completion event plus the outcome the
    serial path would have produced — a ResponseCheckTx where check_tx
    would return one, the exact exception instance where it would raise."""

    __slots__ = ("done", "outcome")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.outcome: object = None

    def wait(self):
        """Block until the shared batch resolves; re-raise or return
        exactly as the serial check_tx would."""
        self.done.wait()
        if isinstance(self.outcome, BaseException):
            raise self.outcome
        return self.outcome


# Shutdown sentinel: stop() enqueues it; the executor drains up to it,
# resolves everything in flight, and exits (a later submit restarts).
_STOP = object()


class IngestCoalescer:
    """The mempool's batching executor. Lazy: the thread spawns on the
    first submission (a mempool that never sees front-door traffic costs
    nothing); daemonized, so it never blocks teardown — and stop() lets a
    torn-down node release the thread (and its strong mempool/app refs)
    instead of parking it forever."""

    def __init__(self, mempool) -> None:
        self.mempool = mempool
        self._q: "queue.Queue[tuple[bytes, str, PendingCheckTx]]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._thread_mtx = threading.Lock()
        self._stopping = False
        # observability counters (read by the mempool_ingest bench and the
        # ingest tests; plain ints — the GIL makes += atomic enough)
        self.batches = 0          # shared check_tx_batch dispatches issued
        self.requests = 0         # txs submitted
        self.coalesced_txs = 0    # txs that shared a batch with >=1 other
        self.max_coalesced = 0    # most txs sharing one batch

    def submit(self, tx: bytes, sender: str = "") -> PendingCheckTx:
        """Queue one tx; returns the caller's pending. Never blocks beyond
        the queue put. Put and executor lifecycle share one mutex with
        stop(), so a submission can never land BEHIND the shutdown
        sentinel of a queue whose executor is exiting — after a stop(),
        the next submit starts a fresh queue + executor."""
        p = PendingCheckTx()
        self.requests += 1
        with self._thread_mtx:
            if self._stopping:
                # the old executor drains its queue up to the sentinel and
                # dies; this submission belongs to a fresh generation
                self._stopping = False
                self._q = queue.Queue()
                self._thread = None
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, args=(self._q,),
                    name="mempool-ingest", daemon=True)
                self._thread.start()
            self._q.put((tx, sender, p))
        return p

    def stop(self) -> None:
        """Release the executor: everything already queued still resolves
        (all puts are ordered before the sentinel by the shared mutex),
        then the thread exits and drops its mempool/app references. Node
        teardown calls this so a churned-out node can't leak a parked
        thread per restart; a later submit simply restarts the executor."""
        with self._thread_mtx:
            if self._thread is not None and self._thread.is_alive():
                self._stopping = True
                self._q.put(_STOP)

    def _collect(self, q, first) -> tuple[list, bool]:
        """The continuous-batching step: drain submissions arriving within
        the coalescing window (or already queued), bounded by max_batch.
        Returns (batch, stop) — stop when the shutdown sentinel was
        drained mid-window (the batch still processes first; nothing can
        follow the sentinel on this queue)."""
        batch = [first]
        cap = max_batch()
        deadline = _time.monotonic() + window_us() / 1e6
        while len(batch) < cap:
            remaining = deadline - _time.monotonic()
            try:
                item = (q.get(timeout=remaining) if remaining > 0
                        else q.get_nowait())
            except queue.Empty:
                break
            if item is _STOP:
                return batch, True
            batch.append(item)
        return batch, False

    def _run(self, q) -> None:
        while True:
            batch = []
            stopping = False
            try:
                first = q.get()
                if first is _STOP:
                    return
                batch, stopping = self._collect(q, first)
                self.batches += 1
                self.max_coalesced = max(self.max_coalesced, len(batch))
                if len(batch) > 1:
                    self.coalesced_txs += len(batch)
                self._observe(batch)
                outcomes = self.mempool.check_tx_batch(
                    [tx for (tx, _, _) in batch],
                    [sender for (_, sender, _) in batch])
                for (_, _, p), o in zip(batch, outcomes):
                    p.outcome = o
                    p.done.set()
                if stopping:
                    return
            except Exception as e:  # noqa: BLE001 - the executor must never
                # die: a stranded done-event would hang an RPC handler or a
                # gossip recv thread forever. Waiters get the error (their
                # wait() re-raises it, exactly where the serial path would
                # have surfaced it).
                for (_, _, p) in batch:
                    if not p.done.is_set():
                        p.outcome = e
                        p.done.set()
                if stopping:
                    return

    def _observe(self, batch) -> None:
        """Coalescing marker on the owning node's flight recorder + the
        pre-seeded ingest counters; observability must never be able to
        strand a batch, so failures are swallowed."""
        try:
            tr = self.mempool.tracer
            if tr is not None and tr.enabled:
                tr.record("mempool.ingest_coalesce", 0.0,
                          requests=len(batch))
            from tendermint_tpu.utils import metrics as tmmetrics

            m = tmmetrics.GLOBAL_NODE_METRICS
            if m is not None and len(batch) > 1:
                m.ingest_coalesced.add(len(batch))
        except Exception:  # noqa: BLE001 - observability never blocks txs
            pass
