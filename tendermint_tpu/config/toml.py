"""Config TOML rendering + loading (reference: config/toml.go).

Writing uses a template mirroring the reference's section layout; reading
uses stdlib tomllib.
"""

from __future__ import annotations

import tomllib
from dataclasses import fields as dc_fields

from tendermint_tpu.config.config import Config


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, (tuple, list)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


_SECTIONS = [
    ("", "base"),
    ("rpc", "rpc"),
    ("p2p", "p2p"),
    ("mempool", "mempool"),
    ("statesync", "statesync"),
    ("fastsync", "fastsync"),
    ("consensus", "consensus"),
    ("storage", "storage"),
    ("tx_index", "tx_index"),
    ("instrumentation", "instrumentation"),
]


def write_config_toml(cfg: Config, path: str) -> None:
    lines = ["# tendermint-tpu node configuration", ""]
    for section, attr in _SECTIONS:
        obj = getattr(cfg, attr)
        if section:
            lines.append(f"[{section}]")
        for f in dc_fields(obj):
            if f.name == "root_dir":
                continue
            lines.append(f"{f.name} = {_toml_value(getattr(obj, f.name))}")
        lines.append("")
    with open(path, "w") as fh:
        fh.write("\n".join(lines))


def load_toml_into(cfg: Config, path: str) -> Config:
    with open(path, "rb") as fh:
        doc = tomllib.load(fh)
    for section, attr in _SECTIONS:
        obj = getattr(cfg, attr)
        src = doc if section == "" else doc.get(section, {})
        for f in dc_fields(obj):
            if f.name in src and f.name != "root_dir":
                val = src[f.name]
                if isinstance(getattr(obj, f.name), tuple) and isinstance(val, list):
                    val = tuple(val)
                setattr(obj, f.name, val)
    return cfg
