"""Config TOML rendering + loading (reference: config/toml.go).

Writing uses a template mirroring the reference's section layout; reading
uses stdlib tomllib when available (3.11+), else a minimal parser covering
exactly the subset write_config_toml emits.
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # Python 3.10: no tomllib, no tomli in image
    tomllib = None

from dataclasses import fields as dc_fields

from tendermint_tpu.config.config import Config


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, (tuple, list)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'


_SECTIONS = [
    ("", "base"),
    ("rpc", "rpc"),
    ("p2p", "p2p"),
    ("mempool", "mempool"),
    ("statesync", "statesync"),
    ("fastsync", "fastsync"),
    ("consensus", "consensus"),
    ("storage", "storage"),
    ("tx_index", "tx_index"),
    ("instrumentation", "instrumentation"),
]


def write_config_toml(cfg: Config, path: str) -> None:
    lines = ["# tendermint-tpu node configuration", ""]
    for section, attr in _SECTIONS:
        obj = getattr(cfg, attr)
        if section:
            lines.append(f"[{section}]")
        for f in dc_fields(obj):
            if f.name == "root_dir":
                continue
            lines.append(f"{f.name} = {_toml_value(getattr(obj, f.name))}")
        lines.append("")
    with open(path, "w") as fh:
        fh.write("\n".join(lines))


def _parse_scalar(tok: str):
    tok = tok.strip()
    if tok.startswith('"'):
        out, i = [], 1
        while i < len(tok):
            c = tok[i]
            if c == "\\" and i + 1 < len(tok):
                out.append(tok[i + 1])
                i += 2
                continue
            if c == '"':
                break
            out.append(c)
            i += 1
        return "".join(out)
    if tok == "true":
        return True
    if tok == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        return float(tok)


def _split_array_items(body: str) -> list:
    items, depth, in_str, esc, cur = [], 0, False, False, []
    for c in body:
        if in_str:
            cur.append(c)
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
            continue
        if c == '"':
            in_str = True
            cur.append(c)
        elif c == "[":
            depth += 1
            cur.append(c)
        elif c == "]":
            depth -= 1
            cur.append(c)
        elif c == "," and depth == 0:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    tail = "".join(cur).strip()
    if tail:
        items.append(tail)
    return items


def _parse_value(tok: str):
    tok = tok.strip()
    if tok.startswith("["):
        body = tok[1:tok.rindex("]")]
        return [_parse_value(item) for item in _split_array_items(body)]
    return _parse_scalar(tok)


def _strip_comment(line: str) -> str:
    in_str = esc = False
    for i, c in enumerate(line):
        if in_str:
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c == "#":
            return line[:i]
    return line


def parse_toml_minimal(text: str) -> dict:
    """Parse the TOML subset write_config_toml emits (flat key = value
    lines under optional [section] headers; strings, bools, ints, floats,
    one-line arrays, # comments)."""
    doc: dict = {}
    cur = doc
    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            name = line[1:-1].strip()
            cur = doc.setdefault(name, {})
            continue
        key, _, val = line.partition("=")
        cur[key.strip()] = _parse_value(val)
    return doc


def load_toml_into(cfg: Config, path: str) -> Config:
    if tomllib is not None:
        with open(path, "rb") as fh:
            doc = tomllib.load(fh)
    else:
        with open(path, "r") as fh:
            doc = parse_toml_minimal(fh.read())
    for section, attr in _SECTIONS:
        obj = getattr(cfg, attr)
        src = doc if section == "" else doc.get(section, {})
        for f in dc_fields(obj):
            if f.name in src and f.name != "root_dir":
                val = src[f.name]
                if isinstance(getattr(obj, f.name), tuple) and isinstance(val, list):
                    val = tuple(val)
                setattr(obj, f.name, val)
    return cfg
