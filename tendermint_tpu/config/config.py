"""Node configuration (reference: config/config.go:66-96,923-1100).

Flat dataclasses mirroring the reference's TOML sections; see
tendermint_tpu.config.toml for the file rendering.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class BaseConfig:
    """reference: config/config.go:180-300."""

    root_dir: str = ""
    proxy_app: str = "kvstore"
    moniker: str = "anonymous"
    fast_sync_mode: bool = True
    db_backend: str = "sqlite"
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "plain"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    abci: str = "socket"
    filter_peers: bool = False

    def resolve(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.root_dir, path)


@dataclass
class RPCConfig:
    """reference: config/config.go:320-480."""

    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: tuple = ()
    grpc_laddr: str = ""
    grpc_max_open_connections: int = 900
    unsafe: bool = False
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_s: float = 10.0
    max_body_bytes: int = 1000000
    max_header_bytes: int = 1 << 20
    pprof_laddr: str = ""
    # broadcast_tx_* admission gate (docs/OVERLOAD.md): concurrent
    # CheckTx-holding requests beyond this get a typed overload error
    # instead of queuing unboundedly on the mempool lock. 0 disables.
    max_broadcast_tx_inflight: int = 256


@dataclass
class P2PConfig:
    """reference: config/config.go:500-640."""

    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    upnp: bool = False
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    unconditional_peer_ids: str = ""
    persistent_peers_max_dial_period_s: float = 0.0
    flush_throttle_timeout_s: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout_s: float = 20.0
    dial_timeout_s: float = 3.0
    # Overload-resilience plane (utils/peerscore.py, docs/OVERLOAD.md):
    # decaying per-peer misbehavior scores with escalating sanctions.
    peer_score_halflife_s: float = 120.0   # score decay half-life
    peer_disconnect_score: float = 50.0    # crossing => disconnect (0 = off)
    peer_ban_score: float = 100.0          # crossing => timed ban (0 = off)
    peer_ban_duration_s: float = 30.0      # first ban; doubles per re-offense
    peer_ban_max_duration_s: float = 600.0
    # Per-peer per-channel inbound message ceilings, msgs/s token buckets
    # ("<ch>:<rate>,..." e.g. "0x22:2000,0x30:4000,0x61:200"; empty = off).
    # Over-limit deliveries are scored and dropped, never processed.
    recv_msg_rate: str = ""


@dataclass
class MempoolConfig:
    """reference: config/config.go:660-760."""

    version: str = "v0"
    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    size: int = 5000
    max_txs_bytes: int = 1024 * 1024 * 1024
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1024 * 1024
    max_batch_bytes: int = 0
    ttl_duration_s: float = 0.0
    ttl_num_blocks: int = 0


@dataclass
class StateSyncConfig:
    """reference: config/config.go:780-860."""

    enable: bool = False
    temp_dir: str = ""
    rpc_servers: tuple = ()
    trust_period_s: float = 168 * 3600.0
    trust_height: int = 0
    trust_hash: str = ""
    discovery_time_s: float = 15.0
    chunk_request_timeout_s: float = 10.0
    chunk_fetchers: int = 4


@dataclass
class FastSyncConfig:
    """reference: config/config.go:880-910."""

    version: str = "v0"


@dataclass
class ConsensusConfig:
    """Timeouts in seconds (reference: config/config.go:923-1050)."""

    wal_path: str = "data/cs.wal"
    timeout_propose_s: float = 3.0
    timeout_propose_delta_s: float = 0.5
    timeout_prevote_s: float = 1.0
    timeout_prevote_delta_s: float = 0.5
    timeout_precommit_s: float = 1.0
    timeout_precommit_delta_s: float = 0.5
    timeout_commit_s: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_s: float = 0.0
    peer_gossip_sleep_duration_s: float = 0.1
    peer_query_maj23_sleep_duration_s: float = 2.0
    double_sign_check_height: int = 0
    # Stall watchdog (consensus/watchdog.py): hand the node back to
    # fast-sync catchup when no height commits for watchdog_stall_multiple
    # × the expected block interval while peers report heights at least
    # watchdog_peer_lead ahead. 0 disables the watchdog entirely.
    watchdog_stall_multiple: float = 12.0
    watchdog_peer_lead: int = 2

    # reference: config/config.go Propose/Prevote/Precommit/Commit helpers
    def propose(self, round_: int) -> float:
        return self.timeout_propose_s + self.timeout_propose_delta_s * round_

    def prevote(self, round_: int) -> float:
        return self.timeout_prevote_s + self.timeout_prevote_delta_s * round_

    def precommit(self, round_: int) -> float:
        return self.timeout_precommit_s + self.timeout_precommit_delta_s * round_

    def commit_time_s(self) -> float:
        return self.timeout_commit_s

    def wait_for_txs(self) -> bool:
        return not self.create_empty_blocks or self.create_empty_blocks_interval_s > 0

    def watchdog_stall_s(self) -> float:
        """Seconds of no-commit progress before the stall watchdog may
        recover. TMTPU_WATCHDOG_STALL_S overrides as an absolute value
        (chaos tests shrink it without rewriting config files)."""
        env = os.environ.get("TMTPU_WATCHDOG_STALL_S")
        if env:
            return float(env)
        expected = self.timeout_commit_s + self.timeout_propose_s
        return self.watchdog_stall_multiple * max(expected, 0.1)


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False


@dataclass
class TxIndexConfig:
    indexer: str = "kv"
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "tendermint"


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    fastsync: FastSyncConfig = field(default_factory=FastSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)

    def set_root(self, root: str) -> "Config":
        self.base.root_dir = root
        return self

    def genesis_file(self) -> str:
        return self.base.resolve(self.base.genesis_file)

    def priv_validator_key_file(self) -> str:
        return self.base.resolve(self.base.priv_validator_key_file)

    def priv_validator_state_file(self) -> str:
        return self.base.resolve(self.base.priv_validator_state_file)

    def node_key_file(self) -> str:
        return self.base.resolve(self.base.node_key_file)

    def db_dir(self) -> str:
        return self.base.resolve(self.base.db_dir)

    def wal_file(self) -> str:
        return self.base.resolve(self.consensus.wal_path)

    def validate_basic(self) -> None:
        for name, v in (
            ("timeout_propose", self.consensus.timeout_propose_s),
            ("timeout_prevote", self.consensus.timeout_prevote_s),
            ("timeout_precommit", self.consensus.timeout_precommit_s),
            ("timeout_commit", self.consensus.timeout_commit_s),
        ):
            if v < 0:
                raise ValueError(f"{name} can't be negative")
        if self.mempool.size < 0:
            raise ValueError("mempool size can't be negative")


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """Fast timeouts for in-process tests (reference: config/config.go
    TestConfig)."""
    c = Config()
    c.consensus.timeout_propose_s = 0.8
    c.consensus.timeout_propose_delta_s = 0.1
    c.consensus.timeout_prevote_s = 0.2
    c.consensus.timeout_prevote_delta_s = 0.1
    c.consensus.timeout_precommit_s = 0.2
    c.consensus.timeout_precommit_delta_s = 0.1
    c.consensus.timeout_commit_s = 0.05
    c.consensus.skip_timeout_commit = True
    c.base.db_backend = "memdb"
    return c
