"""In-process kvstore example application (reference: abci/example/kvstore/
kvstore.go + persistent_kvstore.go).

Transactions are "key=value" (or raw bytes stored under themselves); a
"val:<b64pubkey>!<power>" tx updates the validator set, like the reference's
persistent kvstore. AppHash = big-endian tx count, matching the reference's
size-based app hash semantics.
"""

from __future__ import annotations

import base64
import hashlib
import struct

from tendermint_tpu.abci import types as abci
from tendermint_tpu.store.db import DB, MemDB

VALIDATOR_TX_PREFIX = b"val:"


def _ed25519_address(pub_key_bytes: bytes) -> bytes:
    """The ed25519 validator address rule: SHA-256 truncated to 20 bytes
    (crypto/ed25519.PubKey.address; this app only registers ed25519 keys)."""
    return hashlib.sha256(pub_key_bytes).digest()[:20]


SNAPSHOT_FORMAT = 1
SNAPSHOT_CHUNK_SIZE = 16 * 1024
RETAIN_SNAPSHOTS = 4


class KVStoreApplication(abci.Application):
    def __init__(self, db: DB | None = None, snapshot_interval: int = 0):
        self.db = db if db is not None else MemDB()
        self.size = 0
        self.height = 0
        self.app_hash = b""
        self.val_updates: list[abci.ValidatorUpdate] = []
        self.validators: dict[bytes, int] = {}  # pubkey bytes -> power
        # address -> pubkey, for slashing byzantine validators reported by
        # address in BeginBlock (reference: persistent_kvstore.go
        # valAddrToPubKeyMap)
        self.addr_to_pubkey: dict[bytes, bytes] = {}
        # snapshot support (reference: the e2e app, test/e2e/app/app.go;
        # the reference kvstore itself has none)
        self.snapshot_interval = snapshot_interval
        self._snapshots: list[tuple[abci.Snapshot, list[bytes]]] = []
        self._restore: tuple[abci.Snapshot, list[bytes]] | None = None
        self._load_state()

    # --- state persistence -------------------------------------------------

    def _load_state(self) -> None:
        raw = self.db.get(b"__state__")
        if raw:
            self.size, self.height = struct.unpack(">QQ", raw[:16])
            self.app_hash = raw[16:]

    def _save_state(self) -> None:
        self.db.set(b"__state__", struct.pack(">QQ", self.size, self.height) + self.app_hash)

    # --- ABCI --------------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f'{{"size":{self.size}}}',
            version="0.1.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash if self.height else b"",
        )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self._apply_validator_update(vu)
        return abci.ResponseInitChain()

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX) and not self._parse_val_tx(req.tx):
            return abci.ResponseCheckTx(code=1, log="invalid validator tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        self.val_updates = []
        # Slash byzantine validators to zero power (reference:
        # abci/example/kvstore/persistent_kvstore.go:140-170: the persistent
        # kvstore punishes DUPLICATE_VOTE; light-client attacks carry the
        # same attributable signatures, so both slash here).
        for ev in req.byzantine_validators:
            if ev.type not in (abci.EVIDENCE_TYPE_DUPLICATE_VOTE,
                               abci.EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK):
                continue
            if ev.validator is None:
                continue
            pk = self.addr_to_pubkey.get(ev.validator.address)
            if pk is None:
                continue
            vu = abci.ValidatorUpdate("ed25519", pk, 0)
            self.val_updates.append(vu)
            self._apply_validator_update(vu)
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        tx = req.tx
        if tx.startswith(VALIDATOR_TX_PREFIX):
            parsed = self._parse_val_tx(tx)
            if not parsed:
                return abci.ResponseDeliverTx(code=1, log="invalid validator tx")
            vu = abci.ValidatorUpdate("ed25519", parsed[0], parsed[1])
            self.val_updates.append(vu)
            self._apply_validator_update(vu)
        else:
            if b"=" in tx:
                k, v = tx.split(b"=", 1)
            else:
                k = v = tx
            self.db.set(b"kv:" + k, v)
        self.size += 1
        events = [abci.Event(type="app", attributes=[
            abci.EventAttribute(key=b"creator", value=b"kvstore", index=True),
        ])]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock(validator_updates=list(self.val_updates))

    def commit(self) -> abci.ResponseCommit:
        self.app_hash = struct.pack(">Q", self.size)
        self.height += 1
        self._save_state()
        if self.snapshot_interval and self.height % self.snapshot_interval == 0:
            self._take_snapshot()
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/val":
            power = self.validators.get(req.data, 0)
            return abci.ResponseQuery(code=0, key=req.data, value=str(power).encode())
        v = self.db.get(b"kv:" + req.data)
        if v is None:
            return abci.ResponseQuery(code=0, key=req.data, log="does not exist")
        return abci.ResponseQuery(code=0, key=req.data, value=v, log="exists")

    # --- snapshots (serving + restore) --------------------------------------

    def _serialize_state(self) -> bytes:
        """Full app state as one blob: size/height/app_hash, validators,
        kv pairs (length-prefixed, deterministic key order)."""
        out = [struct.pack(">QQB", self.size, self.height, len(self.app_hash)),
               self.app_hash]
        vals = sorted(self.validators.items())
        out.append(struct.pack(">I", len(vals)))
        for pk, power in vals:
            out.append(struct.pack(">Hq", len(pk), power) + pk)
        kvs = list(self.db.iterator(b"kv:", b"kv;"))
        out.append(struct.pack(">I", len(kvs)))
        for k, v in kvs:
            out.append(struct.pack(">II", len(k), len(v)) + k + v)
        return b"".join(out)

    def _deserialize_state(self, blob: bytes) -> None:
        off = 17
        size, height, hlen = struct.unpack(">QQB", blob[:off])
        app_hash = blob[off:off + hlen]; off += hlen
        (nvals,) = struct.unpack(">I", blob[off:off + 4]); off += 4
        validators = {}
        for _ in range(nvals):
            plen, power = struct.unpack(">Hq", blob[off:off + 10]); off += 10
            validators[blob[off:off + plen]] = power; off += plen
        (nkv,) = struct.unpack(">I", blob[off:off + 4]); off += 4
        pairs = []
        for _ in range(nkv):
            klen, vlen = struct.unpack(">II", blob[off:off + 8]); off += 8
            k = blob[off:off + klen]; off += klen
            pairs.append((k, blob[off:off + vlen])); off += vlen
        # install atomically only after a full parse
        self.size, self.height, self.app_hash = size, height, app_hash
        self.validators = validators
        self.addr_to_pubkey = {_ed25519_address(pk): pk for pk in validators}
        for k, v in pairs:
            self.db.set(k, v)
        self._save_state()

    def _take_snapshot(self) -> None:
        import hashlib

        blob = self._serialize_state()
        chunks = [blob[i:i + SNAPSHOT_CHUNK_SIZE]
                  for i in range(0, len(blob), SNAPSHOT_CHUNK_SIZE)] or [b""]
        snap = abci.Snapshot(height=self.height, format=SNAPSHOT_FORMAT,
                             chunks=len(chunks),
                             hash=hashlib.sha256(blob).digest())
        self._snapshots.append((snap, chunks))
        self._snapshots = self._snapshots[-RETAIN_SNAPSHOTS:]

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots(snapshots=[s for s, _ in self._snapshots])

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        for s, chunks in self._snapshots:
            if (s.height == req.height and s.format == req.format
                    and 0 <= req.chunk < len(chunks)):
                return abci.ResponseLoadSnapshotChunk(chunk=chunks[req.chunk])
        return abci.ResponseLoadSnapshotChunk()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        s = req.snapshot
        if s is None or s.format != SNAPSHOT_FORMAT:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT_FORMAT)
        if s.chunks <= 0 or len(s.hash) != 32:
            return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_REJECT)
        self._restore = (s, [])
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        import hashlib

        if self._restore is None:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ABORT)
        snap, chunks = self._restore
        if req.index != len(chunks):
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY,
                refetch_chunks=[len(chunks)])
        chunks.append(req.chunk)
        if len(chunks) < snap.chunks:
            return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)
        blob = b"".join(chunks)
        self._restore = None
        if hashlib.sha256(blob).digest() != snap.hash:
            # corrupt payload: refetch everything, distrust the senders
            self._restore = (snap, [])
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY_SNAPSHOT,
                reject_senders=[req.sender] if req.sender else [])
        try:
            self._deserialize_state(blob)
        except Exception:  # noqa: BLE001 - malformed snapshot must not crash
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_REJECT_SNAPSHOT)
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)

    # --- helpers -----------------------------------------------------------

    def _apply_validator_update(self, vu: abci.ValidatorUpdate) -> None:
        addr = _ed25519_address(vu.pub_key_bytes)
        if vu.power == 0:
            self.validators.pop(vu.pub_key_bytes, None)
            self.addr_to_pubkey.pop(addr, None)
        else:
            self.validators[vu.pub_key_bytes] = vu.power
            self.addr_to_pubkey[addr] = vu.pub_key_bytes

    @staticmethod
    def _parse_val_tx(tx: bytes):
        try:
            body = tx[len(VALIDATOR_TX_PREFIX):].decode()
            pk_b64, power_s = body.split("!", 1)
            return base64.b64decode(pk_b64), int(power_s)
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def make_val_tx(pub_key_bytes: bytes, power: int) -> bytes:
        return VALIDATOR_TX_PREFIX + base64.b64encode(pub_key_bytes) + b"!%d" % power
