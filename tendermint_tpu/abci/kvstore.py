"""In-process kvstore example application (reference: abci/example/kvstore/
kvstore.go + persistent_kvstore.go).

Transactions are "key=value" (or raw bytes stored under themselves); a
"val:<b64pubkey>!<power>" tx updates the validator set, like the reference's
persistent kvstore. AppHash = big-endian tx count, matching the reference's
size-based app hash semantics.
"""

from __future__ import annotations

import base64
import struct

from tendermint_tpu.abci import types as abci
from tendermint_tpu.store.db import DB, MemDB

VALIDATOR_TX_PREFIX = b"val:"


class KVStoreApplication(abci.Application):
    def __init__(self, db: DB | None = None):
        self.db = db if db is not None else MemDB()
        self.size = 0
        self.height = 0
        self.app_hash = b""
        self.val_updates: list[abci.ValidatorUpdate] = []
        self.validators: dict[bytes, int] = {}  # pubkey bytes -> power
        self._load_state()

    # --- state persistence -------------------------------------------------

    def _load_state(self) -> None:
        raw = self.db.get(b"__state__")
        if raw:
            self.size, self.height = struct.unpack(">QQ", raw[:16])
            self.app_hash = raw[16:]

    def _save_state(self) -> None:
        self.db.set(b"__state__", struct.pack(">QQ", self.size, self.height) + self.app_hash)

    # --- ABCI --------------------------------------------------------------

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=f'{{"size":{self.size}}}',
            version="0.1.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash if self.height else b"",
        )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self._apply_validator_update(vu)
        return abci.ResponseInitChain()

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX) and not self._parse_val_tx(req.tx):
            return abci.ResponseCheckTx(code=1, log="invalid validator tx")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        self.val_updates = []
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        tx = req.tx
        if tx.startswith(VALIDATOR_TX_PREFIX):
            parsed = self._parse_val_tx(tx)
            if not parsed:
                return abci.ResponseDeliverTx(code=1, log="invalid validator tx")
            vu = abci.ValidatorUpdate("ed25519", parsed[0], parsed[1])
            self.val_updates.append(vu)
            self._apply_validator_update(vu)
        else:
            if b"=" in tx:
                k, v = tx.split(b"=", 1)
            else:
                k = v = tx
            self.db.set(b"kv:" + k, v)
        self.size += 1
        events = [abci.Event(type="app", attributes=[
            abci.EventAttribute(key=b"creator", value=b"kvstore", index=True),
        ])]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return abci.ResponseEndBlock(validator_updates=list(self.val_updates))

    def commit(self) -> abci.ResponseCommit:
        self.app_hash = struct.pack(">Q", self.size)
        self.height += 1
        self._save_state()
        return abci.ResponseCommit(data=self.app_hash)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "/val":
            power = self.validators.get(req.data, 0)
            return abci.ResponseQuery(code=0, key=req.data, value=str(power).encode())
        v = self.db.get(b"kv:" + req.data)
        if v is None:
            return abci.ResponseQuery(code=0, key=req.data, log="does not exist")
        return abci.ResponseQuery(code=0, key=req.data, value=v, log="exists")

    # --- helpers -----------------------------------------------------------

    def _apply_validator_update(self, vu: abci.ValidatorUpdate) -> None:
        if vu.power == 0:
            self.validators.pop(vu.pub_key_bytes, None)
        else:
            self.validators[vu.pub_key_bytes] = vu.power

    @staticmethod
    def _parse_val_tx(tx: bytes):
        try:
            body = tx[len(VALIDATOR_TX_PREFIX):].decode()
            pk_b64, power_s = body.split("!", 1)
            return base64.b64decode(pk_b64), int(power_s)
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def make_val_tx(pub_key_bytes: bytes, power: int) -> bytes:
        return VALIDATOR_TX_PREFIX + base64.b64encode(pub_key_bytes) + b"!%d" % power
