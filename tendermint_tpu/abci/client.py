"""ABCI socket client: the Application interface over a TCP/unix socket
(reference: abci/client/socket_client.go:27).

Drop-in for an in-process Application: implements the same 13 methods with
the same request/response dataclasses, so Mempool/BlockExecutor/Syncer don't
know whether the app is in-process or remote. Thread-safe; one in-flight
request at a time per client (the proxy gives each subsystem its own client,
so consensus is never blocked behind mempool traffic).
"""

from __future__ import annotations

import socket
import threading
import time

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire
from tendermint_tpu.utils import faults


class ABCIClientError(Exception):
    pass


class ABCISocketClient:
    def __init__(self, addr: str, timeout_s: float = 10.0,
                 connect_retries: int = 20, retry_interval_s: float = 0.25):
        self.addr = addr
        self.timeout_s = timeout_s
        self._retries = connect_retries
        self._retry_interval = retry_interval_s
        self._mtx = threading.Lock()
        self._sock: socket.socket | None = None
        self._rfile = None
        self._wfile = None
        # None = unprobed: the first check_tx_batch sends an EMPTY batch
        # probe (structural — no app code runs, so an error can only mean
        # the server doesn't know the wire extension, whatever its error
        # wording); True/False is the cached verdict (docs/INGEST.md)
        self._batch_checktx: bool | None = None
        # same probe discipline for the deliver_tx_batch extension
        # (fields 21/22, docs/EXECUTION.md)
        self._batch_delivertx: bool | None = None
        self._connect(connect_retries, retry_interval_s)

    def _connect(self, retries: int, interval: float) -> None:
        proto_, rest = self.addr.split("://", 1)
        last_err = None
        for _ in range(max(retries, 1)):
            try:
                if proto_ == "unix":
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.settimeout(self.timeout_s)
                    s.connect(rest)
                elif proto_ == "tcp":
                    host, port = rest.rsplit(":", 1)
                    s = socket.create_connection((host, int(port)),
                                                 timeout=self.timeout_s)
                else:
                    raise ABCIClientError(f"unsupported address {self.addr!r}")
                self._sock = s
                self._rfile = s.makefile("rb")
                self._wfile = s.makefile("wb")
                return
            except OSError as e:
                last_err = e
                time.sleep(interval)
        raise ABCIClientError(f"could not connect to {self.addr}: {last_err}")

    def close(self) -> None:
        with self._mtx:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _call(self, kind: str, req=None):
        faults.fire("abci.call")
        with self._mtx:
            if self._sock is None:
                raise ABCIClientError("client is closed")
            try:
                wire.write_delimited(self._wfile, wire.encode_request(kind, req))
                self._wfile.flush()
                buf = wire.read_delimited(self._rfile)
            except (OSError, EOFError) as e:
                raise ABCIClientError(f"ABCI connection failed: {e}") from e
            if buf is None:
                raise ABCIClientError("ABCI server closed the connection")
            got_kind, resp = wire.decode_response(buf)
            if got_kind != kind:
                raise ABCIClientError(
                    f"unexpected response {got_kind!r} to request {kind!r}")
            return resp

    # --- the Application surface -------------------------------------------

    def echo(self, msg: str) -> str:
        return self._call(wire.ECHO, msg)

    def flush(self) -> None:
        self._call(wire.FLUSH)

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return self._call("info", req)

    def set_option(self, key: str, value: str) -> abci.ResponseSetOption:
        return self._call("set_option", (key, value))

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return self._call("query", req)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return self._call("check_tx", req)

    def check_tx_batch(self, req: abci.RequestCheckTxBatch) -> abci.ResponseCheckTxBatch:
        """One round trip for a whole micro-batch (wire extension fields
        19/20). Support is PROBED structurally on first use: an empty
        batch never reaches app code, so any error response can only mean
        the server doesn't decode the extension oneof (a reference v0.34
        app) — that verdict is cached and this client degrades to the
        serial per-tx loop for good. App exceptions and transport faults
        on REAL batches propagate untouched: they say nothing about batch
        support, and the mempool layer already degrades that one call to
        its serial loop."""
        if self._batch_checktx is None:
            try:
                self._call("check_tx_batch",
                           abci.RequestCheckTxBatch(txs=[], type=req.type))
                self._batch_checktx = True
            except (wire.ABCIRemoteError, ABCIClientError):
                # unknown-request answer (and, for servers that tear the
                # connection down after it, a dead socket): no extension
                self._batch_checktx = False
                self._reconnect()
        if self._batch_checktx:
            return self._call("check_tx_batch", req)
        return abci.ResponseCheckTxBatch(responses=[
            self.check_tx(abci.RequestCheckTx(tx=tx, type=req.type))
            for tx in req.txs
        ])

    def _reconnect(self) -> None:
        """Atomic close+redial under the client mutex, so a concurrent
        _call can never land in the socketless window (and two concurrent
        reconnects can't leak an fd)."""
        with self._mtx:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            self._connect(self._retries, self._retry_interval)

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return self._call("init_chain", req)

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        return self._call("begin_block", req)

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return self._call("deliver_tx", req)

    def deliver_tx_batch(self, req: abci.RequestDeliverTxBatch) -> abci.ResponseDeliverTxBatch:
        """One round trip for a whole block chunk (wire extension fields
        21/22), probed exactly like check_tx_batch: the first use sends an
        EMPTY batch — structural, no app code runs, so an error can only
        mean the server doesn't decode the extension — and the verdict is
        cached for the connection's lifetime. Errors on REAL batches
        propagate untouched: DeliverTx mutates app state, so the caller
        must see the serial loop's exact failure shape (prefix executed,
        then raise) rather than a silent retry that would double-apply."""
        if self._batch_delivertx is None:
            try:
                self._call("deliver_tx_batch", abci.RequestDeliverTxBatch(txs=[]))
                self._batch_delivertx = True
            except (wire.ABCIRemoteError, ABCIClientError):
                # unknown-request answer (and, for servers that tear the
                # connection down after it, a dead socket): no extension
                self._batch_delivertx = False
                self._reconnect()
        if self._batch_delivertx:
            return self._call("deliver_tx_batch", req)
        return abci.ResponseDeliverTxBatch(responses=[
            self.deliver_tx(abci.RequestDeliverTx(tx=tx)) for tx in req.txs
        ])

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return self._call("end_block", req)

    def commit(self) -> abci.ResponseCommit:
        return self._call(wire.COMMIT)

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        return self._call("list_snapshots", req)

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req: abci.RequestLoadSnapshotChunk) -> abci.ResponseLoadSnapshotChunk:
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req: abci.RequestApplySnapshotChunk) -> abci.ResponseApplySnapshotChunk:
        return self._call("apply_snapshot_chunk", req)
