"""ABCI wire codec: Request/Response oneof encoding + varint-delimited
framing (reference: proto/tendermint/abci/types.proto, abci/types/messages.go
WriteMessage/ReadMessage).

Oneof field numbers match the reference proto exactly, so this codec is
wire-compatible with a Go tendermint v0.34 socket app:
  Request:  echo=1 flush=2 info=3 set_option=4 init_chain=5 query=6
            begin_block=7 check_tx=8 deliver_tx=9 end_block=10 commit=11
            list_snapshots=12 offer_snapshot=13 load_snapshot_chunk=14
            apply_snapshot_chunk=15
  Response: exception=1 echo=2 flush=3 info=4 set_option=5 init_chain=6
            query=7 begin_block=8 check_tx=9 deliver_tx=10 end_block=11
            commit=12 list_snapshots=13 offer_snapshot=14
            load_snapshot_chunk=15 apply_snapshot_chunk=16

Extensions (NOT in the reference proto):
  check_tx_batch rides Request field 19 / Response field 20 (this tree's
  ingestion front door, docs/INGEST.md); deliver_tx_batch rides Request
  field 21 / Response field 22 (the batched execution plane,
  docs/EXECUTION.md). Clients fall back to serial per-tx loops against
  pre-batch servers.
"""

from __future__ import annotations

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding import proto

# reference: abci/types/messages.go:12-26
MAX_MSG_SIZE = 100 * 1024 * 1024


# --- framing (uvarint length prefix, reference libs/protoio) ----------------


def write_delimited(sock_file, msg: bytes) -> None:
    sock_file.write(proto.encode_uvarint(len(msg)) + msg)


def read_delimited(sock_file) -> bytes | None:
    """Returns None on clean EOF; raises on truncation/oversize."""
    shift = 0
    length = 0
    while True:
        b = sock_file.read(1)
        if not b:
            if shift == 0:
                return None
            raise EOFError("truncated varint length prefix")
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("varint length prefix too long")
    if length > MAX_MSG_SIZE:
        raise ValueError(f"message size {length} exceeds {MAX_MSG_SIZE}")
    out = b""
    while len(out) < length:
        chunk = sock_file.read(length - len(out))
        if not chunk:
            raise EOFError("truncated message body")
        out += chunk
    return out


# --- sub-message codecs -----------------------------------------------------


def _ts(seconds: int, nanos: int) -> bytes:
    return proto.Writer().varint(1, seconds).varint(2, nanos).out()


def _snapshot_marshal(s: abci.Snapshot) -> bytes:
    return (proto.Writer().uvarint(1, s.height).uvarint(2, s.format)
            .uvarint(3, s.chunks).bytes(4, s.hash).bytes(5, s.metadata).out())


def _snapshot_unmarshal(buf: bytes) -> abci.Snapshot:
    f = proto.fields(buf)
    return abci.Snapshot(
        height=f.get(1, [0])[-1], format=f.get(2, [0])[-1],
        chunks=f.get(3, [0])[-1], hash=f.get(4, [b""])[-1],
        metadata=f.get(5, [b""])[-1])


def _abci_validator_marshal(v: abci.ABCIValidator) -> bytes:
    # power is field 3 in the reference proto (types.proto Validator)
    return proto.Writer().bytes(1, v.address).varint(3, v.power).out()


def _abci_validator_unmarshal(buf: bytes) -> abci.ABCIValidator:
    f = proto.fields(buf)
    return abci.ABCIValidator(address=f.get(1, [b""])[-1],
                              power=proto.as_sint64(f.get(3, [0])[-1]))


def _last_commit_info_marshal(lci: abci.LastCommitInfo) -> bytes:
    w = proto.Writer().varint(1, lci.round)
    for v in lci.votes:
        inner = proto.Writer().message(
            1, _abci_validator_marshal(v.validator), always=True
        ).bool(2, v.signed_last_block).out()
        w.message(2, inner, always=True)
    return w.out()


def _last_commit_info_unmarshal(buf: bytes) -> abci.LastCommitInfo:
    f = proto.fields(buf)
    votes = []
    for vb in f.get(2, []):
        vf = proto.fields(vb)
        votes.append(abci.VoteInfo(
            validator=_abci_validator_unmarshal(vf.get(1, [b""])[-1]),
            signed_last_block=bool(vf.get(2, [0])[-1])))
    return abci.LastCommitInfo(round=proto.as_sint64(f.get(1, [0])[-1]),
                               votes=votes)


def _evidence_marshal(e: abci.ABCIEvidence) -> bytes:
    return (proto.Writer().varint(1, e.type)
            .message(2, _abci_validator_marshal(e.validator), always=True)
            .varint(3, e.height)
            .message(4, _ts(e.time_seconds, e.time_nanos), always=True)
            .varint(5, e.total_voting_power).out())


def _evidence_unmarshal(buf: bytes) -> abci.ABCIEvidence:
    f = proto.fields(buf)
    tsf = proto.fields(f.get(4, [b""])[-1])
    return abci.ABCIEvidence(
        type=proto.as_sint64(f.get(1, [0])[-1]),
        validator=_abci_validator_unmarshal(f.get(2, [b""])[-1]),
        height=proto.as_sint64(f.get(3, [0])[-1]),
        time_seconds=proto.as_sint64(tsf.get(1, [0])[-1]),
        time_nanos=proto.as_sint64(tsf.get(2, [0])[-1]),
        total_voting_power=proto.as_sint64(f.get(5, [0])[-1]))


def _events_marshal(w: proto.Writer, fieldnum: int, events) -> None:
    for e in events:
        w.message(fieldnum, e.marshal(), always=True)


def _check_tx_resp_marshal(resp: abci.ResponseCheckTx) -> bytes:
    cw = (proto.Writer().uvarint(1, resp.code).bytes(2, resp.data)
          .string(3, resp.log).string(4, resp.info)
          .varint(5, resp.gas_wanted).varint(6, resp.gas_used))
    _events_marshal(cw, 7, resp.events)
    cw.string(8, resp.codespace).string(9, resp.sender)
    cw.varint(10, resp.priority).string(11, resp.mempool_error)
    return cw.out()


def _check_tx_resp_unmarshal(buf: bytes) -> abci.ResponseCheckTx:
    from tendermint_tpu.abci.types import Event

    m = proto.fields(buf)
    return abci.ResponseCheckTx(
        code=m.get(1, [0])[-1], data=m.get(2, [b""])[-1],
        log=m.get(3, [b""])[-1].decode() if 3 in m else "",
        info=m.get(4, [b""])[-1].decode() if 4 in m else "",
        gas_wanted=proto.as_sint64(m.get(5, [0])[-1]),
        gas_used=proto.as_sint64(m.get(6, [0])[-1]),
        events=[Event.unmarshal(b) for b in m.get(7, [])],
        codespace=m.get(8, [b""])[-1].decode() if 8 in m else "",
        sender=m.get(9, [b""])[-1].decode() if 9 in m else "",
        priority=proto.as_sint64(m.get(10, [0])[-1]),
        mempool_error=m.get(11, [b""])[-1].decode() if 11 in m else "")


# --- request encode/decode --------------------------------------------------

ECHO, FLUSH, COMMIT = "echo", "flush", "commit"


def encode_request(kind: str, req=None) -> bytes:
    w = proto.Writer()
    if kind == ECHO:
        w.message(1, proto.Writer().string(1, req or "").out(), always=True)
    elif kind == FLUSH:
        w.message(2, b"", always=True)
    elif kind == "info":
        inner = (proto.Writer().string(1, req.version)
                 .uvarint(2, req.block_version).uvarint(3, req.p2p_version).out())
        w.message(3, inner, always=True)
    elif kind == "init_chain":
        iw = proto.Writer().message(1, _ts(req.time_seconds, req.time_nanos), always=True)
        iw.string(2, req.chain_id)
        if req.consensus_params is not None:
            iw.message(3, req.consensus_params.marshal(), always=True)
        for v in req.validators:
            iw.message(4, v.marshal(), always=True)
        iw.bytes(5, req.app_state_bytes).varint(6, req.initial_height)
        w.message(5, iw.out(), always=True)
    elif kind == "query":
        inner = (proto.Writer().bytes(1, req.data).string(2, req.path)
                 .varint(3, req.height).bool(4, req.prove).out())
        w.message(6, inner, always=True)
    elif kind == "begin_block":
        bw = proto.Writer().bytes(1, req.hash)
        if req.header is not None:
            bw.message(2, req.header.marshal(), always=True)
        bw.message(3, _last_commit_info_marshal(req.last_commit_info), always=True)
        for e in req.byzantine_validators:
            bw.message(4, _evidence_marshal(e), always=True)
        w.message(7, bw.out(), always=True)
    elif kind == "check_tx":
        inner = proto.Writer().bytes(1, req.tx).varint(2, req.type).out()
        w.message(8, inner, always=True)
    elif kind == "check_tx_batch":
        # extension field (not in the reference proto): the ingestion
        # front door's one-round-trip micro-batch (docs/INGEST.md)
        bw = proto.Writer()
        for t in req.txs:
            # message(always=True), not bytes(): a repeated element must
            # be emitted even when empty, or the batch shape collapses
            bw.message(1, t, always=True)
        bw.varint(2, req.type)
        w.message(19, bw.out(), always=True)
    elif kind == "deliver_tx":
        w.message(9, proto.Writer().bytes(1, req.tx).out(), always=True)
    elif kind == "deliver_tx_batch":
        # extension field (not in the reference proto): one round trip
        # executes a whole block chunk (docs/EXECUTION.md)
        bw = proto.Writer()
        for t in req.txs:
            # message(always=True), not bytes(): a repeated element must
            # be emitted even when empty, or the batch shape collapses
            bw.message(1, t, always=True)
        w.message(21, bw.out(), always=True)
    elif kind == "end_block":
        w.message(10, proto.Writer().varint(1, req.height).out(), always=True)
    elif kind == COMMIT:
        w.message(11, b"", always=True)
    elif kind == "list_snapshots":
        w.message(12, b"", always=True)
    elif kind == "offer_snapshot":
        ow = proto.Writer()
        if req.snapshot is not None:
            ow.message(1, _snapshot_marshal(req.snapshot), always=True)
        ow.bytes(2, req.app_hash)
        w.message(13, ow.out(), always=True)
    elif kind == "load_snapshot_chunk":
        inner = (proto.Writer().uvarint(1, req.height).uvarint(2, req.format)
                 .uvarint(3, req.chunk).out())
        w.message(14, inner, always=True)
    elif kind == "apply_snapshot_chunk":
        inner = (proto.Writer().uvarint(1, req.index).bytes(2, req.chunk)
                 .string(3, req.sender).out())
        w.message(15, inner, always=True)
    elif kind == "set_option":
        key, value = req
        inner = proto.Writer().string(1, key).string(2, value).out()
        w.message(4, inner, always=True)
    else:
        raise ValueError(f"unknown request kind {kind!r}")
    return w.out()


def decode_request(buf: bytes) -> tuple[str, object]:
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.params import ConsensusParams

    f = proto.fields(buf)
    if 1 in f:
        return ECHO, proto.fields(f[1][-1]).get(1, [b""])[-1].decode()
    if 2 in f:
        return FLUSH, None
    if 3 in f:
        m = proto.fields(f[3][-1])
        return "info", abci.RequestInfo(
            version=m.get(1, [b""])[-1].decode() if 1 in m else "",
            block_version=m.get(2, [0])[-1], p2p_version=m.get(3, [0])[-1])
    if 5 in f:
        m = proto.fields(f[5][-1])
        tsf = proto.fields(m.get(1, [b""])[-1])
        return "init_chain", abci.RequestInitChain(
            time_seconds=proto.as_sint64(tsf.get(1, [0])[-1]),
            time_nanos=proto.as_sint64(tsf.get(2, [0])[-1]),
            chain_id=m.get(2, [b""])[-1].decode() if 2 in m else "",
            consensus_params=ConsensusParams.unmarshal(m[3][-1]) if 3 in m else None,
            validators=[abci.ValidatorUpdate.unmarshal(b) for b in m.get(4, [])],
            app_state_bytes=m.get(5, [b""])[-1],
            initial_height=proto.as_sint64(m.get(6, [0])[-1]))
    if 6 in f:
        m = proto.fields(f[6][-1])
        return "query", abci.RequestQuery(
            data=m.get(1, [b""])[-1],
            path=m.get(2, [b""])[-1].decode() if 2 in m else "",
            height=proto.as_sint64(m.get(3, [0])[-1]),
            prove=bool(m.get(4, [0])[-1]))
    if 7 in f:
        m = proto.fields(f[7][-1])
        return "begin_block", abci.RequestBeginBlock(
            hash=m.get(1, [b""])[-1],
            header=Header.unmarshal(m[2][-1]) if 2 in m else None,
            last_commit_info=_last_commit_info_unmarshal(m.get(3, [b""])[-1]),
            byzantine_validators=[_evidence_unmarshal(b) for b in m.get(4, [])])
    if 8 in f:
        m = proto.fields(f[8][-1])
        return "check_tx", abci.RequestCheckTx(
            tx=m.get(1, [b""])[-1], type=proto.as_sint64(m.get(2, [0])[-1]))
    if 9 in f:
        return "deliver_tx", abci.RequestDeliverTx(
            tx=proto.fields(f[9][-1]).get(1, [b""])[-1])
    if 10 in f:
        return "end_block", abci.RequestEndBlock(
            height=proto.as_sint64(proto.fields(f[10][-1]).get(1, [0])[-1]))
    if 11 in f:
        return COMMIT, None
    if 12 in f:
        return "list_snapshots", abci.RequestListSnapshots()
    if 13 in f:
        m = proto.fields(f[13][-1])
        return "offer_snapshot", abci.RequestOfferSnapshot(
            snapshot=_snapshot_unmarshal(m[1][-1]) if 1 in m else None,
            app_hash=m.get(2, [b""])[-1])
    if 14 in f:
        m = proto.fields(f[14][-1])
        return "load_snapshot_chunk", abci.RequestLoadSnapshotChunk(
            height=m.get(1, [0])[-1], format=m.get(2, [0])[-1],
            chunk=m.get(3, [0])[-1])
    if 15 in f:
        m = proto.fields(f[15][-1])
        return "apply_snapshot_chunk", abci.RequestApplySnapshotChunk(
            index=m.get(1, [0])[-1], chunk=m.get(2, [b""])[-1],
            sender=m.get(3, [b""])[-1].decode() if 3 in m else "")
    if 19 in f:  # extension: batched CheckTx (docs/INGEST.md)
        m = proto.fields(f[19][-1])
        return "check_tx_batch", abci.RequestCheckTxBatch(
            txs=list(m.get(1, [])),
            type=proto.as_sint64(m.get(2, [0])[-1]))
    if 21 in f:  # extension: batched DeliverTx (docs/EXECUTION.md)
        m = proto.fields(f[21][-1])
        return "deliver_tx_batch", abci.RequestDeliverTxBatch(
            txs=list(m.get(1, [])))
    if 4 in f:  # set_option (deprecated in the reference, kept for parity)
        m = proto.fields(f[4][-1])
        return "set_option", (
            m.get(1, [b""])[-1].decode() if 1 in m else "",
            m.get(2, [b""])[-1].decode() if 2 in m else "")
    raise ValueError("unknown/empty ABCI request")


# --- response encode/decode -------------------------------------------------


def encode_response(kind: str, resp=None, error: str | None = None) -> bytes:
    w = proto.Writer()
    if error is not None:
        w.message(1, proto.Writer().string(1, error).out(), always=True)
        return w.out()
    if kind == ECHO:
        w.message(2, proto.Writer().string(1, resp or "").out(), always=True)
    elif kind == FLUSH:
        w.message(3, b"", always=True)
    elif kind == "info":
        inner = (proto.Writer().string(1, resp.data).string(2, resp.version)
                 .uvarint(3, resp.app_version).varint(4, resp.last_block_height)
                 .bytes(5, resp.last_block_app_hash).out())
        w.message(4, inner, always=True)
    elif kind == "set_option":
        inner = (proto.Writer().uvarint(1, resp.code).string(3, resp.log)
                 .string(4, resp.info).out())
        w.message(5, inner, always=True)
    elif kind == "init_chain":
        iw = proto.Writer()
        if resp.consensus_params is not None:
            iw.message(1, resp.consensus_params.marshal(), always=True)
        for v in resp.validators:
            iw.message(2, v.marshal(), always=True)
        iw.bytes(3, resp.app_hash)
        w.message(6, iw.out(), always=True)
    elif kind == "query":
        inner = (proto.Writer().uvarint(1, resp.code).string(3, resp.log)
                 .string(4, resp.info).varint(5, resp.index).bytes(6, resp.key)
                 .bytes(7, resp.value).varint(9, resp.height)
                 .string(10, resp.codespace).out())
        w.message(7, inner, always=True)
    elif kind == "begin_block":
        bw = proto.Writer()
        _events_marshal(bw, 1, resp.events)
        w.message(8, bw.out(), always=True)
    elif kind == "check_tx":
        w.message(9, _check_tx_resp_marshal(resp), always=True)
    elif kind == "check_tx_batch":
        bw = proto.Writer()
        for rtx in resp.responses:
            bw.message(1, _check_tx_resp_marshal(rtx), always=True)
        w.message(20, bw.out(), always=True)
    elif kind == "deliver_tx":
        w.message(10, resp.marshal(), always=True)
    elif kind == "deliver_tx_batch":
        bw = proto.Writer()
        for rtx in resp.responses:
            bw.message(1, rtx.marshal(), always=True)
        w.message(22, bw.out(), always=True)
    elif kind == "end_block":
        ew = proto.Writer()
        for v in resp.validator_updates:
            ew.message(1, v.marshal(), always=True)
        if resp.consensus_param_updates is not None:
            ew.message(2, resp.consensus_param_updates.marshal(), always=True)
        _events_marshal(ew, 3, resp.events)
        w.message(11, ew.out(), always=True)
    elif kind == COMMIT:
        inner = (proto.Writer().bytes(2, resp.data)
                 .varint(3, resp.retain_height).out())
        w.message(12, inner, always=True)
    elif kind == "list_snapshots":
        lw = proto.Writer()
        for s in resp.snapshots:
            lw.message(1, _snapshot_marshal(s), always=True)
        w.message(13, lw.out(), always=True)
    elif kind == "offer_snapshot":
        w.message(14, proto.Writer().varint(1, resp.result).out(), always=True)
    elif kind == "load_snapshot_chunk":
        w.message(15, proto.Writer().bytes(1, resp.chunk).out(), always=True)
    elif kind == "apply_snapshot_chunk":
        aw = proto.Writer().varint(1, resp.result)
        for c in resp.refetch_chunks:
            aw.uvarint(2, c)
        for s in resp.reject_senders:
            aw.string(3, s)
        w.message(16, aw.out(), always=True)
    else:
        raise ValueError(f"unknown response kind {kind!r}")
    return w.out()


class ABCIRemoteError(Exception):
    """Server sent ResponseException (reference: abci/client/socket_client.go
    error handling)."""


def decode_response(buf: bytes) -> tuple[str, object]:
    from tendermint_tpu.types.params import ConsensusParams

    f = proto.fields(buf)
    if 1 in f:
        msg = proto.fields(f[1][-1]).get(1, [b""])[-1].decode()
        raise ABCIRemoteError(msg)
    if 2 in f:
        return ECHO, proto.fields(f[2][-1]).get(1, [b""])[-1].decode()
    if 3 in f:
        return FLUSH, None
    if 4 in f:
        m = proto.fields(f[4][-1])
        return "info", abci.ResponseInfo(
            data=m.get(1, [b""])[-1].decode() if 1 in m else "",
            version=m.get(2, [b""])[-1].decode() if 2 in m else "",
            app_version=m.get(3, [0])[-1],
            last_block_height=proto.as_sint64(m.get(4, [0])[-1]),
            last_block_app_hash=m.get(5, [b""])[-1])
    if 5 in f:
        m = proto.fields(f[5][-1])
        return "set_option", abci.ResponseSetOption(
            code=m.get(1, [0])[-1],
            log=m.get(3, [b""])[-1].decode() if 3 in m else "",
            info=m.get(4, [b""])[-1].decode() if 4 in m else "")
    if 6 in f:
        m = proto.fields(f[6][-1])
        return "init_chain", abci.ResponseInitChain(
            consensus_params=ConsensusParams.unmarshal(m[1][-1]) if 1 in m else None,
            validators=[abci.ValidatorUpdate.unmarshal(b) for b in m.get(2, [])],
            app_hash=m.get(3, [b""])[-1])
    if 7 in f:
        m = proto.fields(f[7][-1])
        return "query", abci.ResponseQuery(
            code=m.get(1, [0])[-1],
            log=m.get(3, [b""])[-1].decode() if 3 in m else "",
            info=m.get(4, [b""])[-1].decode() if 4 in m else "",
            index=proto.as_sint64(m.get(5, [0])[-1]),
            key=m.get(6, [b""])[-1], value=m.get(7, [b""])[-1],
            height=proto.as_sint64(m.get(9, [0])[-1]),
            codespace=m.get(10, [b""])[-1].decode() if 10 in m else "")
    if 8 in f:
        from tendermint_tpu.abci.types import Event

        m = proto.fields(f[8][-1])
        return "begin_block", abci.ResponseBeginBlock(
            events=[Event.unmarshal(b) for b in m.get(1, [])])
    if 9 in f:
        return "check_tx", _check_tx_resp_unmarshal(f[9][-1])
    if 20 in f:  # extension: batched CheckTx (docs/INGEST.md)
        m = proto.fields(f[20][-1])
        return "check_tx_batch", abci.ResponseCheckTxBatch(
            responses=[_check_tx_resp_unmarshal(b) for b in m.get(1, [])])
    if 22 in f:  # extension: batched DeliverTx (docs/EXECUTION.md)
        m = proto.fields(f[22][-1])
        return "deliver_tx_batch", abci.ResponseDeliverTxBatch(
            responses=[abci.ResponseDeliverTx.unmarshal(b) for b in m.get(1, [])])
    if 10 in f:
        return "deliver_tx", abci.ResponseDeliverTx.unmarshal(f[10][-1])
    if 11 in f:
        from tendermint_tpu.abci.types import Event

        m = proto.fields(f[11][-1])
        return "end_block", abci.ResponseEndBlock(
            validator_updates=[abci.ValidatorUpdate.unmarshal(b) for b in m.get(1, [])],
            consensus_param_updates=(ConsensusParams.unmarshal(m[2][-1])
                                     if 2 in m else None),
            events=[Event.unmarshal(b) for b in m.get(3, [])])
    if 12 in f:
        m = proto.fields(f[12][-1])
        return COMMIT, abci.ResponseCommit(
            data=m.get(2, [b""])[-1],
            retain_height=proto.as_sint64(m.get(3, [0])[-1]))
    if 13 in f:
        m = proto.fields(f[13][-1])
        return "list_snapshots", abci.ResponseListSnapshots(
            snapshots=[_snapshot_unmarshal(b) for b in m.get(1, [])])
    if 14 in f:
        return "offer_snapshot", abci.ResponseOfferSnapshot(
            result=proto.as_sint64(proto.fields(f[14][-1]).get(1, [0])[-1]))
    if 15 in f:
        return "load_snapshot_chunk", abci.ResponseLoadSnapshotChunk(
            chunk=proto.fields(f[15][-1]).get(1, [b""])[-1])
    if 16 in f:
        m = proto.fields(f[16][-1])
        return "apply_snapshot_chunk", abci.ResponseApplySnapshotChunk(
            result=proto.as_sint64(m.get(1, [0])[-1]),
            refetch_chunks=list(m.get(2, [])),
            reject_senders=[b.decode() for b in m.get(3, [])])
    raise ValueError("unknown/empty ABCI response")
