"""ABCI socket server: runs an Application behind a TCP or unix socket
(reference: abci/server/socket_server.go).

One global app mutex serializes requests across all connections, exactly
like the reference (socket_server.go:19 "concurrency is not allowed").
Responses go back on the connection the request arrived on, in order.
"""

from __future__ import annotations

import os
import socket
import threading

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire


def _dispatch(app, kind: str, req):
    if kind == wire.ECHO:
        return req
    if kind == wire.FLUSH:
        return None
    if kind == wire.COMMIT:
        return app.commit()
    if kind == "set_option":
        return app.set_option(*req)
    return getattr(app, kind)(req)


class ABCIServer:
    """reference: abci/server/socket_server.go:21 SocketServer."""

    def __init__(self, app: abci.Application, addr: str, logger=None):
        self.app = app
        self.addr = addr
        self.logger = logger
        self._app_mtx = threading.Lock()
        self._listener: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._running = False
        self._accept_thread: threading.Thread | None = None

    def start(self) -> None:
        proto_, rest = self.addr.split("://", 1)
        if proto_ == "unix":
            if os.path.exists(rest):
                os.unlink(rest)
            self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._listener.bind(rest)
        elif proto_ == "tcp":
            host, port = rest.rsplit(":", 1)
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, int(port)))
            if int(port) == 0:
                host_, port_ = self._listener.getsockname()[:2]
                self.addr = f"tcp://{host_}:{port_}"
        else:
            raise ValueError(f"unsupported ABCI server address {self.addr!r}")
        self._listener.listen(8)
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_routine, name="abci-accept", daemon=True)
        self._accept_thread.start()

    def stop(self) -> None:
        self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass

    def _accept_routine(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                self._conns.append(conn)
                threading.Thread(target=self._conn_routine, args=(conn,),
                                 daemon=True).start()
            except Exception:  # noqa: BLE001 - one bad conn must not kill
                # the accept loop (the server would refuse forever after)
                try:
                    conn.close()
                except OSError:
                    pass

    def _conn_routine(self, conn: socket.socket) -> None:
        """reference: socket_server.go:164 handleRequests."""
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        try:
            while self._running:
                buf = wire.read_delimited(rfile)
                if buf is None:
                    return
                try:
                    kind, req = wire.decode_request(buf)
                except ValueError as e:
                    wire.write_delimited(
                        wfile, wire.encode_response("", error=f"bad request: {e}"))
                    wfile.flush()
                    return
                try:
                    with self._app_mtx:
                        resp = _dispatch(self.app, kind, req)
                    out = wire.encode_response(kind, resp)
                except Exception as e:  # noqa: BLE001 - app panic -> exception resp
                    out = wire.encode_response(kind, error=str(e))
                wire.write_delimited(wfile, out)
                # Flush every response: our clients call synchronously (each
                # request is its own round trip), and eager flushing keeps a
                # pipelining client correct too -- unlike the reference server,
                # which buffers until a Flush request (socket_server.go:164).
                wfile.flush()
        except (EOFError, OSError, ValueError):
            return
        except Exception:  # noqa: BLE001 - unexpected wire/app shapes tear
            # down THIS connection only; the server stays up
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if conn in self._conns:
                self._conns.remove(conn)
