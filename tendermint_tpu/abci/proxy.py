"""AppConns: the 4-connection ABCI proxy multiplexer (reference:
proxy/multi_app_conn.go:21, proxy/client.go:17,75).

Each subsystem gets its own logical connection so consensus block execution
is never queued behind mempool CheckTx traffic:
  consensus -- BeginBlock/DeliverTx/EndBlock/Commit (BlockExecutor, replay)
  mempool   -- CheckTx
  query     -- Info/Query (RPC, handshake)
  snapshot  -- ListSnapshots/OfferSnapshot/...Chunk (state sync)

For an in-process app all four share the app object behind one mutex
(reference: abci/client/local_client.go). For a remote app each connection
is its own socket (reference: proxy/multi_app_conn.go:56-96).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from tendermint_tpu.abci import types as abci

_APP_METHODS = (
    "info", "set_option", "query", "check_tx", "check_tx_batch",
    "init_chain", "begin_block", "deliver_tx", "deliver_tx_batch",
    "end_block", "commit",
    "list_snapshots", "offer_snapshot", "load_snapshot_chunk",
    "apply_snapshot_chunk",
)


class LocalClient:
    """In-proc connection: shared app + shared mutex (reference:
    abci/client/local_client.go:14 -- one mutex across all local clients)."""

    def __init__(self, app: abci.Application, mtx: threading.RLock):
        self._app = app
        self._mtx = mtx

    def __getattr__(self, name):
        if name not in _APP_METHODS:
            raise AttributeError(name)
        fn = getattr(self._app, name)

        def call(*args, **kwargs):
            with self._mtx:
                # serializing app calls IS this mutex's purpose (reference
                # local_client.go holds mtx across the callback)
                return fn(*args, **kwargs)  # tmlint: disable=lock-held-call

        return call

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


@dataclass
class AppConns:
    """reference: proxy/multi_app_conn.go:21 AppConns interface."""

    consensus: object
    mempool: object
    query: object
    snapshot: object

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            close = getattr(c, "close", None)
            if close:
                close()


def local_app_conns(app: abci.Application) -> AppConns:
    """reference: proxy/client.go:33 NewLocalClientCreator."""
    mtx = threading.RLock()
    return AppConns(
        consensus=LocalClient(app, mtx),
        mempool=LocalClient(app, mtx),
        query=LocalClient(app, mtx),
        snapshot=LocalClient(app, mtx),
    )


def socket_app_conns(addr: str, timeout_s: float = 10.0) -> AppConns:
    """Four independent sockets to one app server (reference:
    proxy/client.go:56 NewRemoteClientCreator + multi_app_conn.go:56)."""
    from tendermint_tpu.abci.client import ABCISocketClient

    return AppConns(
        consensus=ABCISocketClient(addr, timeout_s),
        mempool=ABCISocketClient(addr, timeout_s),
        query=ABCISocketClient(addr, timeout_s),
        snapshot=ABCISocketClient(addr, timeout_s),
    )


def grpc_app_conns(addr: str, timeout_s: float = 10.0) -> AppConns:
    """Four independent gRPC channels to one app server (reference:
    proxy/client.go grpc transport)."""
    from tendermint_tpu.abci.grpc_transport import ABCIGrpcClient

    return AppConns(
        consensus=ABCIGrpcClient(addr, timeout_s),
        mempool=ABCIGrpcClient(addr, timeout_s),
        query=ABCIGrpcClient(addr, timeout_s),
        snapshot=ABCIGrpcClient(addr, timeout_s),
    )


def new_app_conns(app_or_addr) -> AppConns:
    """In-proc Application object, or a tcp://|unix:// (socket) or grpc://
    address string."""
    if isinstance(app_or_addr, str):
        if app_or_addr.startswith("grpc://"):
            return grpc_app_conns(app_or_addr)
        return socket_app_conns(app_or_addr)
    return local_app_conns(app_or_addr)
