"""ABCI: the application boundary (reference: abci/types/application.go:11-32,
proto/tendermint/abci/types.proto).

The 13-method Application interface plus request/response dataclasses. The
deterministic subset of ResponseDeliverTx (code/data/gas) feeds
LastResultsHash exactly as the reference's deterministicResponseDeliverTx
(types/results.go:32-43).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.encoding import proto

CODE_TYPE_OK = 0


@dataclass
class EventAttribute:
    key: bytes = b""
    value: bytes = b""
    index: bool = False

    def marshal(self) -> bytes:
        return (
            proto.Writer().bytes(1, self.key).bytes(2, self.value).bool(3, self.index).out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "EventAttribute":
        f = proto.fields(buf)
        return EventAttribute(
            key=f.get(1, [b""])[-1], value=f.get(2, [b""])[-1],
            index=bool(f.get(3, [0])[-1]),
        )


@dataclass
class Event:
    type: str = ""
    attributes: list[EventAttribute] = field(default_factory=list)

    def marshal(self) -> bytes:
        w = proto.Writer().string(1, self.type)
        for a in self.attributes:
            w.message(2, a.marshal(), always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Event":
        f = proto.fields(buf)
        return Event(
            type=f.get(1, [b""])[-1].decode() if 1 in f else "",
            attributes=[EventAttribute.unmarshal(b) for b in f.get(2, [])],
        )


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int

    def marshal(self) -> bytes:
        fieldnum = {"ed25519": 1, "secp256k1": 2}[self.pub_key_type]
        pk = proto.Writer().bytes(fieldnum, self.pub_key_bytes).out()
        return proto.Writer().message(1, pk, always=True).varint(2, self.power).out()

    @staticmethod
    def unmarshal(buf: bytes) -> "ValidatorUpdate":
        f = proto.fields(buf)
        pkf = proto.fields(f.get(1, [b""])[-1])
        if 1 in pkf:
            kt, kb = "ed25519", pkf[1][-1]
        elif 2 in pkf:
            kt, kb = "secp256k1", pkf[2][-1]
        else:
            raise ValueError("empty pubkey in ValidatorUpdate")
        return ValidatorUpdate(kt, kb, proto.as_sint64(f.get(2, [0])[-1]))


@dataclass
class ABCIValidator:
    """abci.Validator: 20-byte address + power (types.proto:341-347)."""

    address: bytes = b""
    power: int = 0


@dataclass
class VoteInfo:
    validator: ABCIValidator
    signed_last_block: bool = False


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: list[VoteInfo] = field(default_factory=list)


EVIDENCE_TYPE_UNKNOWN = 0
EVIDENCE_TYPE_DUPLICATE_VOTE = 1
EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK = 2


@dataclass
class ABCIEvidence:
    type: int = EVIDENCE_TYPE_UNKNOWN
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    height: int = 0
    time_seconds: int = 0
    time_nanos: int = 0
    total_voting_power: int = 0


# --- requests ---------------------------------------------------------------


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class RequestInitChain:
    time_seconds: int = 0
    time_nanos: int = 0
    chain_id: str = ""
    consensus_params: object | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 0


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: object | None = None  # types.Header
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: list[ABCIEvidence] = field(default_factory=list)


CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW


@dataclass
class RequestCheckTxBatch:
    """Batched CheckTx: one ABCI round trip prices a whole micro-batch (no
    reference analogue — the tx ingestion front door, docs/INGEST.md).
    Carried on wire-extension oneof fields 19/20 (abci/wire.py); apps that
    don't override the Application shim get exact per-tx loop semantics."""

    txs: list[bytes] = field(default_factory=list)
    type: int = CHECK_TX_TYPE_NEW


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestDeliverTxBatch:
    """Batched DeliverTx: one ABCI round trip executes a whole block chunk
    (no reference analogue — the batched execution plane, docs/EXECUTION.md).
    Carried on wire-extension oneof fields 21/22 (abci/wire.py); apps that
    don't override the Application shim get exact per-tx loop semantics,
    including the serial loop's failure shape (prefix executed, then raise)."""

    txs: list[bytes] = field(default_factory=list)


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot | None = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


# --- responses --------------------------------------------------------------


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseSetOption:
    code: int = 0
    log: str = ""
    info: str = ""


@dataclass
class ResponseInitChain:
    consensus_params: object | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = 0
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: object | None = None
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseBeginBlock:
    events: list[Event] = field(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseCheckTxBatch:
    """Per-tx responses, order-aligned with RequestCheckTxBatch.txs."""

    responses: list[ResponseCheckTx] = field(default_factory=list)


@dataclass
class ResponseDeliverTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def deterministic_marshal(self) -> bytes:
        """Strip nondeterministic fields before hashing into LastResultsHash
        (reference: types/results.go:32-43)."""
        return (
            proto.Writer()
            .uvarint(1, self.code)
            .bytes(2, self.data)
            .varint(5, self.gas_wanted)
            .varint(6, self.gas_used)
            .out()
        )

    def marshal(self) -> bytes:
        w = (
            proto.Writer()
            .uvarint(1, self.code)
            .bytes(2, self.data)
            .string(3, self.log)
            .string(4, self.info)
            .varint(5, self.gas_wanted)
            .varint(6, self.gas_used)
        )
        for e in self.events:
            w.message(7, e.marshal(), always=True)
        w.string(8, self.codespace)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "ResponseDeliverTx":
        f = proto.fields(buf)
        return ResponseDeliverTx(
            code=f.get(1, [0])[-1],
            data=f.get(2, [b""])[-1],
            log=f.get(3, [b""])[-1].decode() if 3 in f else "",
            info=f.get(4, [b""])[-1].decode() if 4 in f else "",
            gas_wanted=proto.as_sint64(f.get(5, [0])[-1]),
            gas_used=proto.as_sint64(f.get(6, [0])[-1]),
            events=[Event.unmarshal(b) for b in f.get(7, [])],
            codespace=f.get(8, [b""])[-1].decode() if 8 in f else "",
        )


@dataclass
class ResponseDeliverTxBatch:
    """Per-tx responses, order-aligned with RequestDeliverTxBatch.txs."""

    responses: list[ResponseDeliverTx] = field(default_factory=list)


@dataclass
class ResponseEndBlock:
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: object | None = None
    events: list[Event] = field(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""
    retain_height: int = 0


OFFER_SNAPSHOT_UNKNOWN = 0
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

APPLY_CHUNK_UNKNOWN = 0
APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5


@dataclass
class ResponseListSnapshots:
    snapshots: list[Snapshot] = field(default_factory=list)


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_CHUNK_UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


class Application:
    """The 13-method ABCI application interface (reference:
    abci/types/application.go:11-32). Subclass and override."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def set_option(self, key: str, value: str) -> ResponseSetOption:
        return ResponseSetOption()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    def check_tx_batch(self, req: RequestCheckTxBatch) -> ResponseCheckTxBatch:
        """Loop-fallback shim: apps that don't implement batched CheckTx
        get the serial loop's exact per-tx semantics — batching is an
        optimization seam (docs/INGEST.md), never a semantic change."""
        return ResponseCheckTxBatch(responses=[
            self.check_tx(RequestCheckTx(tx=tx, type=req.type))
            for tx in req.txs
        ])

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def deliver_tx_batch(self, req: RequestDeliverTxBatch) -> ResponseDeliverTxBatch:
        """Loop-fallback shim: apps that don't implement batched DeliverTx
        get the serial loop's exact per-tx semantics — if tx k raises, txs
        0..k-1 have already mutated app state and the exception propagates,
        identical to the caller running the loop itself (docs/EXECUTION.md)."""
        return ResponseDeliverTxBatch(responses=[
            self.deliver_tx(RequestDeliverTx(tx=tx)) for tx in req.txs
        ])

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req: RequestLoadSnapshotChunk) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: RequestApplySnapshotChunk) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk()


def results_hash(responses: list[ResponseDeliverTx]) -> bytes:
    """LastResultsHash (reference: types/results.go ABCIResults.Hash)."""
    from tendermint_tpu.crypto import merkle

    return merkle.hash_from_byte_slices(
        [r.deterministic_marshal() for r in responses]
    )
