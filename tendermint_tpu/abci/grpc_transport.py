"""ABCI over gRPC (reference: abci/client/grpc_client.go,
abci/server/grpc_server.go; service tendermint.abci.ABCIApplication).

Reuses the oneof codec from abci/wire.py: each gRPC method carries the BARE
Request*/Response* message, which is exactly the payload of the
corresponding oneof field, so encoding = wrap-with-field-number +
strip-wrapper. No generated stubs; a protoc-built Go client speaks to this
server unchanged.
"""

from __future__ import annotations

from concurrent import futures

import grpc

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire
from tendermint_tpu.encoding import proto

SERVICE = "tendermint.abci.ABCIApplication"

# method name -> (wire kind, request oneof field, response oneof field)
_METHODS = {
    "Echo": (wire.ECHO, 1, 2),
    "Flush": (wire.FLUSH, 2, 3),
    "Info": ("info", 3, 4),
    "SetOption": ("set_option", 4, 5),
    "InitChain": ("init_chain", 5, 6),
    "Query": ("query", 6, 7),
    "BeginBlock": ("begin_block", 7, 8),
    "CheckTx": ("check_tx", 8, 9),
    # extension method (docs/INGEST.md): not in the reference service; a
    # reference server answers UNIMPLEMENTED and the client degrades to
    # the serial loop
    "CheckTxBatch": ("check_tx_batch", 19, 20),
    "DeliverTx": ("deliver_tx", 9, 10),
    # extension method (docs/EXECUTION.md): same contract as CheckTxBatch
    "DeliverTxBatch": ("deliver_tx_batch", 21, 22),
    "EndBlock": ("end_block", 10, 11),
    "Commit": (wire.COMMIT, 11, 12),
    "ListSnapshots": ("list_snapshots", 12, 13),
    "OfferSnapshot": ("offer_snapshot", 13, 14),
    "LoadSnapshotChunk": ("load_snapshot_chunk", 14, 15),
    "ApplySnapshotChunk": ("apply_snapshot_chunk", 15, 16),
}


def _req_to_inner(kind: str, field: int, req) -> bytes:
    buf = wire.encode_request(kind, req)
    return proto.fields(buf).get(field, [b""])[-1]


def _inner_to_req(kind: str, field: int, inner: bytes):
    wrapped = proto.Writer().message(field, inner, always=True).out()
    return wire.decode_request(wrapped)[1]


def _resp_to_inner(kind: str, field: int, resp) -> bytes:
    buf = wire.encode_response(kind, resp)
    return proto.fields(buf).get(field, [b""])[-1]


def _inner_to_resp(kind: str, field: int, inner: bytes):
    wrapped = proto.Writer().message(field, inner, always=True).out()
    return wire.decode_response(wrapped)[1]


class ABCIGrpcServer:
    """reference: abci/server/grpc_server.go."""

    def __init__(self, app: abci.Application, addr: str, max_workers: int = 4):
        import threading

        self._app = app
        self._app_mtx = threading.Lock()  # serialize like the socket server
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        host_port = addr.split("://", 1)[-1]
        port = self._server.add_insecure_port(host_port)
        self.addr = f"tcp://{host_port.rsplit(':', 1)[0]}:{port}"

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    def _dispatch(self, method: str, request: bytes, context) -> bytes:
        from tendermint_tpu.abci.server import _dispatch as app_dispatch

        kind, req_field, resp_field = _METHODS[method]
        try:
            if kind == wire.ECHO:
                msg = proto.fields(request).get(1, [b""])[-1].decode()
                return _resp_to_inner(kind, resp_field, msg)
            if kind == wire.FLUSH:
                return b""
            req = _inner_to_req(kind, req_field, request)
            with self._app_mtx:
                resp = app_dispatch(self._app, kind, req)
            return _resp_to_inner(kind, resp_field, resp)
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return b""

    def _handler(self):
        dispatch = self._dispatch

        class Handler(grpc.GenericRpcHandler):
            def service(self, hcd):
                parts = hcd.method.lstrip("/").split("/")
                if len(parts) != 2 or parts[0] != SERVICE or parts[1] not in _METHODS:
                    return None
                name = parts[1]
                return grpc.unary_unary_rpc_method_handler(
                    lambda request, context: dispatch(name, request, context),
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        return Handler()


class ABCIGrpcClient:
    """Application surface over gRPC -- drop-in like ABCISocketClient
    (reference: abci/client/grpc_client.go)."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        self.timeout_s = timeout_s
        self._batch_checktx = True  # until a server answers UNIMPLEMENTED
        self._batch_delivertx = True  # ditto for DeliverTxBatch
        self._channel = grpc.insecure_channel(addr.split("://", 1)[-1])
        self._calls = {
            name: self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            for name in _METHODS
        }

    def close(self) -> None:
        self._channel.close()

    def _call(self, method: str, req=None):
        kind, req_field, resp_field = _METHODS[method]
        if kind == wire.ECHO:
            inner = proto.Writer().string(1, req or "").out()
        elif req is None:
            inner = b""
        else:
            inner = _req_to_inner(kind, req_field, req)
        try:
            raw = self._calls[method](inner, timeout=self.timeout_s)
        except grpc.RpcError as e:
            # Same error contract as the socket transport: app exceptions
            # surface as ABCIRemoteError, transport faults stay RpcError.
            if e.code() == grpc.StatusCode.INTERNAL:
                raise wire.ABCIRemoteError(e.details()) from e
            raise
        if kind == wire.FLUSH:
            return None
        if kind == wire.ECHO:
            return proto.fields(raw).get(1, [b""])[-1].decode() if raw else ""
        return _inner_to_resp(kind, resp_field, raw)

    def echo(self, msg: str) -> str:
        return self._call("Echo", msg)

    def flush(self) -> None:
        self._call("Flush")

    def info(self, req):
        return self._call("Info", req)

    def set_option(self, key, value):
        return self._call("SetOption", (key, value))

    def query(self, req):
        return self._call("Query", req)

    def check_tx(self, req):
        return self._call("CheckTx", req)

    def check_tx_batch(self, req):
        """One RPC for a whole micro-batch. Only UNIMPLEMENTED — the
        definitive pre-batch-server answer — disables the extension for
        the client's lifetime; transient transport faults and app
        exceptions propagate (the mempool layer degrades that one call to
        its serial loop), so one blip can't silently cost the batching
        win forever."""
        if self._batch_checktx:
            try:
                return self._call("CheckTxBatch", req)
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                self._batch_checktx = False
        return abci.ResponseCheckTxBatch(responses=[
            self.check_tx(abci.RequestCheckTx(tx=tx, type=req.type))
            for tx in req.txs
        ])

    def init_chain(self, req):
        return self._call("InitChain", req)

    def begin_block(self, req):
        return self._call("BeginBlock", req)

    def deliver_tx(self, req):
        return self._call("DeliverTx", req)

    def deliver_tx_batch(self, req):
        """One RPC for a whole block chunk. Only UNIMPLEMENTED disables the
        extension — that status means the method was never routed to app
        code, so falling back to the serial loop cannot double-apply any
        tx. App exceptions (INTERNAL → ABCIRemoteError) and transport
        faults propagate: state may have partially advanced, exactly like
        the serial loop raising mid-block."""
        if self._batch_delivertx:
            try:
                return self._call("DeliverTxBatch", req)
            except grpc.RpcError as e:
                if e.code() != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                self._batch_delivertx = False
        return abci.ResponseDeliverTxBatch(responses=[
            self.deliver_tx(abci.RequestDeliverTx(tx=tx)) for tx in req.txs
        ])

    def end_block(self, req):
        return self._call("EndBlock", req)

    def commit(self):
        return self._call("Commit")

    def list_snapshots(self, req):
        return self._call("ListSnapshots", req)

    def offer_snapshot(self, req):
        return self._call("OfferSnapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("LoadSnapshotChunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("ApplySnapshotChunk", req)
