"""Counter example application (reference: abci/example/counter/counter.go).

A tx is a big-endian integer (at most 8 bytes). In serial mode DeliverTx
requires each tx to equal the current count (a strict nonce) and CheckTx
requires it to be >= the count; Commit's app hash is the big-endian tx
count once any tx has been delivered. Query paths: "hash" (commit count)
and "tx" (tx count). Error codes mirror abci/example/code/code.go.
"""

from __future__ import annotations

from tendermint_tpu.abci import types as abci

CODE_TYPE_ENCODING_ERROR = 1
CODE_TYPE_BAD_NONCE = 2


class CounterApp(abci.Application):
    def __init__(self, serial: bool = False):
        self.serial = serial
        self.hash_count = 0
        self.tx_count = 0

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data='{"hashes":%d,"txs":%d}' % (self.hash_count, self.tx_count))

    def set_option(self, key: str, value: str) -> abci.ResponseSetOption:
        if key == "serial" and value == "on":
            self.serial = True
        return abci.ResponseSetOption()

    def _tx_value(self, tx: bytes) -> int | None:
        return int.from_bytes(tx, "big") if len(tx) <= 8 else None

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        if self.serial:
            value = self._tx_value(req.tx)
            if value is None:
                return abci.ResponseDeliverTx(
                    code=CODE_TYPE_ENCODING_ERROR,
                    log=f"Max tx size is 8 bytes, got {len(req.tx)}")
            if value != self.tx_count:
                return abci.ResponseDeliverTx(
                    code=CODE_TYPE_BAD_NONCE,
                    log=f"Invalid nonce. Expected {self.tx_count}, got {value}")
        self.tx_count += 1
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self.serial:
            value = self._tx_value(req.tx)
            if value is None:
                return abci.ResponseCheckTx(
                    code=CODE_TYPE_ENCODING_ERROR,
                    log=f"Max tx size is 8 bytes, got {len(req.tx)}")
            if value < self.tx_count:
                return abci.ResponseCheckTx(
                    code=CODE_TYPE_BAD_NONCE,
                    log=f"Invalid nonce. Expected >= {self.tx_count}, "
                        f"got {value}")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

    def commit(self) -> abci.ResponseCommit:
        self.hash_count += 1
        if self.tx_count == 0:
            return abci.ResponseCommit()
        return abci.ResponseCommit(data=self.tx_count.to_bytes(8, "big"))

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        if req.path == "hash":
            return abci.ResponseQuery(value=str(self.hash_count).encode())
        if req.path == "tx":
            return abci.ResponseQuery(value=str(self.tx_count).encode())
        return abci.ResponseQuery(
            log=f"Invalid query path. Expected hash or tx, got {req.path}")
