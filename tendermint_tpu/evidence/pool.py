"""Evidence pool: detects, stores, and provides byzantine evidence
(reference: evidence/pool.go, evidence/verify.go:19,113,162).
"""

from __future__ import annotations

import threading

from tendermint_tpu.encoding import proto
from tendermint_tpu.store import envelope
from tendermint_tpu.utils import clock as _clock
from tendermint_tpu.store.db import DB
from tendermint_tpu.utils import faults
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
    LightClientAttackEvidence,
    evidence_unmarshal,
)
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import Vote


def _pending_key(ev) -> bytes:
    return b"p%020d%s" % (ev.height(), ev.hash().hex().encode())


def _committed_key(ev) -> bytes:
    return b"c%020d%s" % (ev.height(), ev.hash().hex().encode())


class EvidencePool:
    def __init__(self, db: DB, state_store, block_store, logger=None,
                 clock=None):
        self._db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = logger
        # per-node time source (utils/clock.py): the one wall-clock read
        # this pool makes (evidence_time fallback when no block meta exists)
        # must follow the node's skewed clock, not the host's
        self.clock = clock if clock is not None else _clock.DEFAULT
        # expiry audit trail (docs/SOAK.md skew auditing): every pending row
        # this pool ages out, with the block/time ages that justified it.
        # The soak auditor asserts no entry was expired while still inside
        # the block-count bound — the invariant clock skew must not break,
        # because expiry requires BOTH ages past their limits and block
        # counts cannot be skewed. Bounded ring; newest last.
        self.expired_log: list[dict] = []
        self._mtx = threading.Lock()
        # votes reported by consensus, to be turned into evidence
        self._consensus_buffer: list[tuple[Vote, Vote]] = []
        self.on_evidence = []  # callbacks(ev) for the reactor broadcast
        # repair hook (docs/DURABILITY.md): wired by the node to its
        # StoreRepairer; corrupt rows are also quarantined inline below —
        # evidence is re-deliverable (gossip) or already decided (a block),
        # so drop-and-requeue-from-peers IS the repair
        self.on_corruption = None
        # Monotonic change counter for the pending set / consensus buffer.
        # The per-peer broadcast routines compare it against their last
        # scan instead of re-running the pending_evidence DB iteration
        # every tick — at fabric scale (300+ peer connections) the idle
        # scans alone were most of a core (e2e/fabric.py, docs/SOAK.md).
        self.version = 0

    # --- queries -----------------------------------------------------------

    def pending_evidence(self, max_bytes: int) -> tuple[list, int]:
        """reference: evidence/pool.go PendingEvidence."""
        self._process_consensus_buffer()
        out = []
        size = 0
        for k, v in list(self._db.iterator(b"p", b"q")):
            try:
                ev = self._decode_row(k, v)
            except envelope.CorruptedStoreError:
                continue  # quarantined by _decode_row; never gossip rot
            if ev is None:
                continue  # drop-rule transient miss: skip, row stays
            sz = len(v)
            if max_bytes >= 0 and size + sz > max_bytes:
                break
            out.append(ev)
            size += sz
        return out, size

    def _decode_row(self, key: bytes, raw: bytes):
        """Checked decode of one evidence row: the fault site + envelope +
        guarded unmarshal, with inline quarantine on detection (evidence is
        the one store where quarantine IS repair — peers regossip pending
        evidence, committed evidence lives in blocks). A ``drop``-rule
        firing returns None — a *transient* read miss, the same semantics
        every other store gives the rule; the row on disk stays intact."""
        raw = faults.mutate_value("store.evidence.load", raw)
        if raw is None:
            return None
        try:
            return envelope.decode(raw, "evidence", key, evidence_unmarshal,
                                   on_corruption=self.on_corruption)
        except envelope.CorruptedStoreError as e:
            envelope.quarantine(self._db, e)
            envelope.count_repair("evidence")
            self.version += 1
            raise

    def is_pending(self, ev) -> bool:
        return self._db.has(_pending_key(ev))

    def is_committed(self, ev) -> bool:
        return self._db.has(_committed_key(ev))

    # --- adding ------------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """reference: evidence/pool.go AddEvidence."""
        with self._mtx:
            if self.is_pending(ev) or self.is_committed(ev):
                return
            self.verify(ev)
            self._db.set(_pending_key(ev), envelope.wrap(ev.bytes()))
            self.version += 1
        for cb in self.on_evidence:
            cb(ev)

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """Buffered until the next height's state is known (reference:
        evidence/pool.go ReportConflictingVotes)."""
        with self._mtx:
            self._consensus_buffer.append((vote_a, vote_b))
            self.version += 1

    def _process_consensus_buffer(self) -> None:
        """reference: evidence/pool.go processConsensusBuffer."""
        with self._mtx:
            buffered, self._consensus_buffer = self._consensus_buffer, []
        if not buffered:
            return
        state = self.state_store.load()
        for vote_a, vote_b in buffered:
            try:
                if vote_a.height == state.last_block_height:
                    val_set = state.last_validators
                    block_meta = self.block_store.load_block_meta(vote_a.height)
                    evidence_time = block_meta.header.time if block_meta else state.last_block_time
                else:
                    val_set = self.state_store.load_validators(vote_a.height)
                    block_meta = self.block_store.load_block_meta(vote_a.height)
                    evidence_time = (block_meta.header.time if block_meta
                                     else Time.from_unix_ns(self.clock.now_ns()))
                ev = DuplicateVoteEvidence.new(vote_a, vote_b, evidence_time, val_set)
                if ev is not None:
                    with self._mtx:
                        if not self.is_pending(ev) and not self.is_committed(ev):
                            self._db.set(_pending_key(ev),
                                         envelope.wrap(ev.bytes()))
                            self.version += 1
                    for cb in self.on_evidence:
                        cb(ev)
            except Exception:  # noqa: BLE001 - can't form evidence; drop
                pass

    def _note_expiry(self, ev, age_blocks: int, age_ns: int, params) -> None:
        """Record one expiry decision (prune or verify-reject) for the soak
        auditor's false-expiry check. List append is GIL-atomic; the ring
        bound keeps hour-scale soaks from growing it unboundedly."""
        self.expired_log.append({
            "height": ev.height(),
            "age_blocks": age_blocks,
            "age_ns": age_ns,
            "max_age_num_blocks": params.max_age_num_blocks,
            "max_age_duration_ns": params.max_age_duration_ns,
        })
        del self.expired_log[:-64]

    # --- verification (reference: evidence/verify.go) ----------------------

    def verify(self, ev) -> None:
        state = self.state_store.load()
        height = state.last_block_height
        ev_params = state.consensus_params.evidence

        # age check (reference: evidence/verify.go:19-60)
        age_blocks = height - ev.height()
        block_meta = self.block_store.load_block_meta(ev.height())
        ev_time = block_meta.header.time if block_meta else ev.time()
        age_ns = state.last_block_time.unix_ns() - ev_time.unix_ns()
        if (age_blocks > ev_params.max_age_num_blocks
                and age_ns > ev_params.max_age_duration_ns):
            self._note_expiry(ev, age_blocks, age_ns, ev_params)
            raise EvidenceError(
                f"evidence from height {ev.height()} is too old; min height is "
                f"{height - ev_params.max_age_num_blocks}", reason="expired"
            )

        if isinstance(ev, DuplicateVoteEvidence):
            val_set = self.state_store.load_validators(ev.height())
            self.verify_duplicate_vote(ev, state.chain_id, val_set)
            # evidence metadata must match what we'd derive
            _, val = val_set.get_by_address(ev.vote_a.validator_address)
            if ev.validator_power != val.voting_power:
                raise EvidenceError(
                    f"evidence has validator power {ev.validator_power} but should be {val.voting_power}",
                    reason="meta_mismatch",
                )
            if ev.total_voting_power != val_set.total_voting_power():
                raise EvidenceError(
                    f"evidence has total power {ev.total_voting_power} but should be "
                    f"{val_set.total_voting_power()}", reason="meta_mismatch"
                )
        elif isinstance(ev, LightClientAttackEvidence):
            self.verify_light_client_attack(ev, state)
        else:
            raise EvidenceError(f"unknown evidence type {type(ev)}")

    @staticmethod
    def verify_duplicate_vote(ev: DuplicateVoteEvidence, chain_id: str, val_set) -> None:
        """reference: evidence/verify.go:162-220. The two vote signatures
        dispatch as ONE BatchVerifier batch so evidence verification shares
        the kernel/sigcache path like every other verify site (the serial
        error order — vote A first — is replayed over the bitmap)."""
        from tendermint_tpu.crypto import batch as crypto_batch

        _, val = val_set.get_by_address(ev.vote_a.validator_address)
        if val is None:
            raise EvidenceError(
                f"address {ev.vote_a.validator_address.hex()} was not a validator at height {ev.height()}",
                reason="unknown_validator",
            )
        va, vb = ev.vote_a, ev.vote_b
        if va.height != vb.height or va.round != vb.round or va.type != vb.type:
            raise EvidenceError("H/R/S does not match")
        if va.validator_address != vb.validator_address:
            raise EvidenceError("validator addresses do not match")
        if va.block_id == vb.block_id:
            raise EvidenceError("block IDs are the same - not duplicate votes")
        if va.block_id.key() >= vb.block_id.key():
            raise EvidenceError("duplicate votes in invalid order")
        pub = val.pub_key
        verifier = crypto_batch.create_batch_verifier()
        verifier.add(pub, va.sign_bytes(chain_id), va.signature)
        verifier.add(pub, vb.sign_bytes(chain_id), vb.signature)
        _, bitmap = verifier.dispatch().resolve()
        if not bitmap[0]:
            raise EvidenceError("invalid signature on vote A", reason="bad_sig")
        if not bitmap[1]:
            raise EvidenceError("invalid signature on vote B", reason="bad_sig")

    def verify_light_client_attack(self, ev: LightClientAttackEvidence, state) -> None:
        """reference: evidence/verify.go:113-160 (batched commit verify via
        the ValidatorSet paths)."""
        ev.validate_basic()
        common_vals = self.state_store.load_validators(ev.common_height)
        sh = ev.conflicting_block.signed_header
        if sh is None or sh.commit is None:
            raise EvidenceError("missing conflicting header/commit")
        if ev.common_height != sh.header.height:
            # skipping verification: 1/3 of common valset must have signed
            common_vals.verify_commit_light_trusting(state.chain_id, sh.commit, (1, 3))
        else:
            vs = ev.conflicting_block.validator_set
            if vs is None:
                raise EvidenceError("missing conflicting validator set")
            vs.verify_commit_light(state.chain_id, sh.commit.block_id,
                                   sh.header.height, sh.commit)
        # the conflicting header must differ from what we committed
        ours = self.block_store.load_block_meta(sh.header.height)
        if ours is not None and ours.block_id.hash == sh.header.hash():
            raise EvidenceError("conflicting block is the same as our block; not an attack")

        # metadata cross-checks (reference: evidence/verify.go:239-280):
        # the byzantine validators, total power, and timestamp the evidence
        # carries must equal what this node derives from its own state.
        if ev.total_voting_power != common_vals.total_voting_power():
            raise EvidenceError(
                f"evidence total power {ev.total_voting_power} != "
                f"{common_vals.total_voting_power()}", reason="meta_mismatch")
        common_meta = self.block_store.load_block_meta(ev.common_height)
        if common_meta is not None and ev.timestamp != common_meta.header.time:
            raise EvidenceError("evidence timestamp != common block time",
                                reason="meta_mismatch")
        trusted = self.block_store.load_block(sh.header.height)
        trusted_commit = (self.block_store.load_block_commit(sh.header.height)
                          or self.block_store.load_seen_commit(sh.header.height))
        if trusted is not None and trusted_commit is not None:
            from tendermint_tpu.types.light_block import SignedHeader

            trusted_sh = SignedHeader(trusted.header, trusted_commit)
            derived = ev.get_byzantine_validators(common_vals, trusted_sh)
            carried = ev.byzantine_validators
            if len(derived) != len(carried):
                raise EvidenceError(
                    f"expected {len(derived)} byzantine validators, "
                    f"evidence names {len(carried)}", reason="meta_mismatch")
            for d, c in zip(derived, carried):
                if d.address != c.address or d.voting_power != c.voting_power:
                    raise EvidenceError(
                        "byzantine validator mismatch: "
                        f"{d.address.hex()}/{d.voting_power} != "
                        f"{c.address.hex()}/{c.voting_power}",
                        reason="meta_mismatch")

    # --- lifecycle hooks ---------------------------------------------------

    def check_evidence(self, state, evidence_list: list) -> None:
        """Validate block evidence before accepting the block (reference:
        evidence/pool.go CheckEvidence)."""
        seen = set()
        for ev in evidence_list:
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            if self.is_committed(ev):
                raise EvidenceError("evidence was already committed")
            if not self.is_pending(ev):
                self.verify(ev)

    def update(self, state, evidence_list: list) -> None:
        """Mark committed + prune expired (reference: evidence/pool.go Update)."""
        with self._mtx:
            sets, deletes = [], []
            for ev in evidence_list:
                sets.append((_committed_key(ev), envelope.wrap(b"\x01")))
                deletes.append(_pending_key(ev))
            self._db.write_batch(sets, deletes)
            # prune expired pending evidence
            params = state.consensus_params.evidence
            for k, v in list(self._db.iterator(b"p", b"q")):
                try:
                    ev = self._decode_row(k, v)
                except envelope.CorruptedStoreError:
                    continue  # quarantined; nothing left to age out
                if ev is None:
                    continue  # transient miss: age it out next update
                age_blocks = state.last_block_height - ev.height()
                age_ns = state.last_block_time.unix_ns() - ev.time().unix_ns()
                if age_blocks > params.max_age_num_blocks and age_ns > params.max_age_duration_ns:
                    self._db.delete(k)
                    self._note_expiry(ev, age_blocks, age_ns, params)
            if evidence_list:
                self.version += 1
        # Convert buffered conflicting votes into DuplicateVoteEvidence now
        # that the height's state is persisted (reference: evidence/pool.go
        # Update -> processConsensusBuffer).
        self._process_consensus_buffer()
