"""Evidence reactor: gossips evidence to peers (reference:
evidence/reactor.go, channel 0x38, proto/tendermint/evidence/types.proto
EvidenceList)."""

from __future__ import annotations

import threading
import time

from tendermint_tpu.encoding import proto
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.types.evidence import EvidenceError, evidence_unmarshal

EVIDENCE_CHANNEL = 0x38
BROADCAST_SLEEP_S = 0.5


def msg_evidence_list(evs: list) -> bytes:
    w = proto.Writer()
    for ev in evs:
        w.message(1, ev.bytes(), always=True)
    return w.out()


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool):
        super().__init__("EVIDENCE")
        self.pool = pool
        self._peer_running: dict[str, bool] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=6)]

    def add_peer(self, peer: Peer) -> None:
        self._peer_running[peer.id] = True
        threading.Thread(target=self._broadcast_routine, args=(peer,), daemon=True).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        self._peer_running.pop(peer.id, None)

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        from tendermint_tpu.state.store import StateStoreError
        from tendermint_tpu.store.envelope import CorruptedStoreError

        f = proto.fields(msg_bytes)
        for raw in f.get(1, []):
            try:
                ev = evidence_unmarshal(raw)
                self.pool.add_evidence(ev)
            except EvidenceError:
                pass
            except CorruptedStoreError:
                # verification tripped over OUR rotten state/block record —
                # the store hook has quarantined + scheduled the repair;
                # dropping the evidence (it regossips) instead of letting
                # the error tear the peer down (thread-crash-surface rule,
                # docs/DURABILITY.md)
                pass
            except StateStoreError:
                # Evidence for a height WE don't have state for yet — a
                # statesync node mid-bootstrap, or a pruned store — is our
                # limitation, not peer misbehavior. Letting the error
                # escape tears the peer down (Switch._on_receive), and
                # since every honest peer gossips the same evidence, a
                # bootstrapping joiner would shed its ENTIRE peer set and
                # strand itself at height 0 (found by the fabric churn
                # scenario, tests/test_fabric.py). Drop it; the evidence
                # still reaches us committed in a block.
                pass

    def _broadcast_routine(self, peer: Peer) -> None:
        sent: set[bytes] = set()
        seen_version = -1
        try:
            while self._peer_running.get(peer.id) and self.switch is not None:
                # Scan the pool only when it CHANGED since our last scan
                # (pool.version): with hundreds of per-peer routines in one
                # process (the scenario fabric), the idle every-tick DB
                # iterations were most of a core while carrying nothing.
                version = self.pool.version
                if version == seen_version:
                    time.sleep(BROADCAST_SLEEP_S)
                    continue
                evs, _sz = self.pool.pending_evidence(-1)
                fresh = [ev for ev in evs if ev.hash() not in sent]
                if not fresh:
                    seen_version = version
                elif peer.try_send(EVIDENCE_CHANNEL, msg_evidence_list(fresh)):
                    sent.update(ev.hash() for ev in fresh)
                    seen_version = version
                time.sleep(BROADCAST_SLEEP_S)
        except Exception as e:  # noqa: BLE001 - gossip ends like a
            # disconnect (peer teardown mid-send); a fresh routine starts
            # on re-add — but say so: a systematic bug here would
            # otherwise stop evidence gossip cluster-wide with no trail
            logger = getattr(self.switch, "logger", None)
            if logger:
                logger.error("evidence broadcast routine ended",
                             peer=peer.id, err=e)
