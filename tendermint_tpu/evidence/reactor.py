"""Evidence reactor: gossips evidence to peers (reference:
evidence/reactor.go, channel 0x38, proto/tendermint/evidence/types.proto
EvidenceList).

Hardening (docs/BYZANTINE.md): a byzantine peer shipping syntactically
valid but UNVERIFIABLE evidence — wrong chain-id or bogus signatures
(bad_sig), expired age, metadata that contradicts our derivation — used to
be silently dropped, an unmetered free shot at the verification CPU. Every
rejection now lands in the pre-seeded ``evidence_rejected_total{reason}``
counter and scores the delivering peer on the PeerScoreBoard
(utils/peerscore.py ``evidence_reject``), so a flood of junk evidence
walks the peer to disconnect/ban like any other protocol violation.
Rejections that are OUR limitation — state we don't have yet
(bootstrapping joiner), our own rotten store rows — stay unscored.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.encoding import proto
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.types.evidence import EvidenceError, evidence_unmarshal

EVIDENCE_CHANNEL = 0x38
BROADCAST_SLEEP_S = 0.5


def msg_evidence_list(evs: list) -> bytes:
    w = proto.Writer()
    for ev in evs:
        w.message(1, ev.bytes(), always=True)
    return w.out()


def _count_rejected(reason: str) -> None:
    """evidence_rejected_total{reason} — pre-seeded over the closed
    EvidenceError.REASONS set in utils/metrics.py."""
    try:
        from tendermint_tpu.utils import metrics as tmmetrics

        m = tmmetrics.GLOBAL_NODE_METRICS
        if m is not None:
            m.evidence_rejected.add(1, reason=reason)
    except Exception:  # noqa: BLE001 - metrics never block gossip handling
        pass


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool):
        super().__init__("EVIDENCE")
        self.pool = pool
        self._peer_running: dict[str, bool] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=6)]

    def add_peer(self, peer: Peer) -> None:
        self._peer_running[peer.id] = True
        threading.Thread(target=self._broadcast_routine, args=(peer,), daemon=True).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        self._peer_running.pop(peer.id, None)

    def _reject(self, peer: Peer, reason: str) -> None:
        _count_rejected(reason)
        board = getattr(self.switch, "scoreboard", None) if self.switch else None
        if board is not None:
            board.record(peer.id, "evidence_reject")

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        from tendermint_tpu.state.store import StateStoreError
        from tendermint_tpu.store.envelope import CorruptedStoreError
        from tendermint_tpu.types.validator_set import ValidatorSetError

        f = proto.fields(msg_bytes)
        for raw in f.get(1, []):
            try:
                ev = evidence_unmarshal(raw)
            except Exception:  # noqa: BLE001 - undecodable bytes on the
                # evidence channel: peer violation, never a crash surface
                self._reject(peer, "malformed")
                continue
            try:
                self.pool.add_evidence(ev)
            except EvidenceError as e:
                self._reject(peer, getattr(e, "reason", "invalid"))
            except ValidatorSetError:
                # commit-verify failure inside verify_light_client_attack
                # (bogus/insufficient signatures on the conflicting block)
                self._reject(peer, "bad_sig")
            except CorruptedStoreError:
                # verification tripped over OUR rotten state/block record —
                # the store hook has quarantined + scheduled the repair;
                # dropping the evidence (it regossips) instead of letting
                # the error tear the peer down (thread-crash-surface rule,
                # docs/DURABILITY.md). Our rot, not peer misbehavior:
                # unscored.
                pass
            except StateStoreError:
                # Evidence for a height WE don't have state for yet — a
                # statesync node mid-bootstrap, or a pruned store — is our
                # limitation, not peer misbehavior. Letting the error
                # escape tears the peer down (Switch._on_receive), and
                # since every honest peer gossips the same evidence, a
                # bootstrapping joiner would shed its ENTIRE peer set and
                # strand itself at height 0 (found by the fabric churn
                # scenario, tests/test_fabric.py). Drop it; the evidence
                # still reaches us committed in a block.
                pass

    def _broadcast_routine(self, peer: Peer) -> None:
        sent: set[bytes] = set()
        seen_version = -1
        try:
            while self._peer_running.get(peer.id) and self.switch is not None:
                # Scan the pool only when it CHANGED since our last scan
                # (pool.version): with hundreds of per-peer routines in one
                # process (the scenario fabric), the idle every-tick DB
                # iterations were most of a core while carrying nothing.
                version = self.pool.version
                if version == seen_version:
                    time.sleep(BROADCAST_SLEEP_S)
                    continue
                evs, _sz = self.pool.pending_evidence(-1)
                fresh = [ev for ev in evs if ev.hash() not in sent]
                if not fresh:
                    seen_version = version
                elif peer.try_send(EVIDENCE_CHANNEL, msg_evidence_list(fresh)):
                    sent.update(ev.hash() for ev in fresh)
                    seen_version = version
                time.sleep(BROADCAST_SLEEP_S)
        except Exception as e:  # noqa: BLE001 - gossip ends like a
            # disconnect (peer teardown mid-send); a fresh routine starts
            # on re-add — but say so: a systematic bug here would
            # otherwise stop evidence gossip cluster-wide with no trail
            logger = getattr(self.switch, "logger", None)
            if logger:
                logger.error("evidence broadcast routine ended",
                             peer=peer.id, err=e)
