"""Node: the DI root wiring stores, ABCI app, mempool, consensus, and p2p
(reference: node/node.go:100,706,941).
"""

from __future__ import annotations

import os

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import Config
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.state_machine import ConsensusState
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch, Transport
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import new_db
from tendermint_tpu.types.events import EventBus
from tendermint_tpu.types.genesis import GenesisDoc


def default_app(name: str):
    """App selection (reference: proxy/client.go:75 DefaultClientCreator):
    a known in-proc app name, or a tcp://|unix:// address of an out-of-process
    ABCI socket server."""
    if name.startswith(("tcp://", "unix://", "grpc://")):
        return name  # resolved to socket/grpc clients by abci.proxy.new_app_conns
    if name in ("kvstore", "persistent_kvstore"):
        # snapshot support for state-sync serving (the reference e2e app
        # takes snapshot_interval from its manifest; env keeps the CLI thin)
        interval = int(os.environ.get("TMTPU_KVSTORE_SNAPSHOT_INTERVAL", "0"))
        return KVStoreApplication(snapshot_interval=interval)
    if name == "counter":
        from tendermint_tpu.abci.counter import CounterApp

        return CounterApp()
    if name == "counter_serial":
        from tendermint_tpu.abci.counter import CounterApp

        return CounterApp(serial=True)
    if name == "noop":
        from tendermint_tpu.abci.types import Application

        return Application()
    raise ValueError(f"unknown proxy app {name!r}")


class Node:
    """reference: node/node.go:706 NewNode."""

    def __init__(self, config: Config, app=None, genesis: GenesisDoc | None = None,
                 priv_validator=None, node_key: NodeKey | None = None,
                 logger=None):
        self.config = config
        if logger is None:
            # real structured logger by default (reference: libs/log); tests
            # pass NopLogger or capture stderr
            from tendermint_tpu.utils.log import new_logger

            logger = new_logger(level=config.base.log_level,
                                fmt=config.base.log_format)
        self.logger = logger

        # DBs (reference: node/node.go:716,235 initDBs)
        backend = config.base.db_backend
        dbdir = config.db_dir()
        self.block_store = BlockStore(new_db(backend, os.path.join(dbdir, "blockstore.db")
                                             if backend != "memdb" else None))
        self.state_store = StateStore(new_db(backend, os.path.join(dbdir, "state.db")
                                             if backend != "memdb" else None))

        # genesis + state. The very first state load is guarded: a corrupt
        # state row is quarantined and rebuilt from the block store when
        # possible; otherwise the empty state routes this node into the
        # normal state-sync / fast-sync bootstrap (store/repair.py,
        # docs/DURABILITY.md) instead of refusing to boot.
        from tendermint_tpu.store.repair import StoreRepairer, recover_state

        self.genesis = genesis if genesis is not None else GenesisDoc.from_file(config.genesis_file())
        state = recover_state(self.state_store, self.block_store, logger,
                              statesync_enabled=config.statesync.enable)
        if state.is_empty():
            state = make_genesis_state(self.genesis)
            self.state_store.save(state)

        # self-healing storage plane: one repairer owns quarantine + the
        # repair queue; every store's detection hook routes into it
        self.store_repairer = StoreRepairer(
            block_store=self.block_store, state_store=self.state_store,
            chain_id=self.genesis.chain_id, logger=logger)
        self.block_store.on_corruption = self.store_repairer.note
        self.state_store.on_corruption = self.store_repairer.note

        # app: in-proc object or socket address -> 4-connection proxy
        # (reference: node/node.go:731 createAndStartProxyAppConns)
        from tendermint_tpu.abci.proxy import new_app_conns

        self.app = app if app is not None else default_app(config.base.proxy_app)
        self.proxy_app = new_app_conns(self.app)

        # ABCI handshake/replay (reference: node/node.go:777 doHandshake)
        from tendermint_tpu.consensus.replay import Handshaker

        self.event_bus = EventBus()
        handshaker = Handshaker(self.state_store, self.block_store, self.genesis)
        state = handshaker.handshake(state, self.proxy_app.consensus)

        # priv validator: remote signer socket, or local file PV
        # (reference: node/node.go:753 createAndStartPrivValidatorSocketClient)
        if priv_validator is None and config.base.priv_validator_laddr:
            from tendermint_tpu.privval.signer import (
                RetrySignerClient,
                SignerClient,
                SignerListenerEndpoint,
            )

            self.signer_endpoint = SignerListenerEndpoint(
                config.base.priv_validator_laddr)
            priv_validator = RetrySignerClient(
                SignerClient(self.signer_endpoint, self.genesis.chain_id))
        elif priv_validator is None and config.base.priv_validator_key_file:
            priv_validator = FilePV.load_or_generate(
                config.priv_validator_key_file(), config.priv_validator_state_file()
            )
        self.priv_validator = priv_validator

        # mempool
        self.mempool = Mempool(
            self.proxy_app.mempool,
            version=config.mempool.version,
            max_txs=config.mempool.size,
            max_txs_bytes=config.mempool.max_txs_bytes,
            cache_size=config.mempool.cache_size,
            max_tx_bytes=config.mempool.max_tx_bytes,
            keep_invalid_txs_in_cache=config.mempool.keep_invalid_txs_in_cache,
            recheck=config.mempool.recheck,
            ttl_duration_s=config.mempool.ttl_duration_s,
            ttl_num_blocks=config.mempool.ttl_num_blocks,
        )
        # admission filters from the current state (reference:
        # node.go:383,404 WithPreCheck/WithPostCheck; refreshed per block
        # by BlockExecutor._commit)
        from tendermint_tpu.state.tx_filter import tx_post_check, tx_pre_check

        self.mempool.pre_check = tx_pre_check(state)
        self.mempool.post_check = tx_post_check(state)

        # per-node time source (utils/clock.py, docs/NEMESIS.md): every
        # consensus/evidence wall-clock read goes through this object, so a
        # fabric skew action (`node.clock.set_skew(...)`) desynchronizes ONE
        # node of an in-process mesh. Born with the process default's skew
        # so TMTPU_CLOCK_SKEW_S also skews a subprocess testnet node.
        from tendermint_tpu.utils import clock as tmclock

        self.clock = tmclock.Clock(skew_s=tmclock.DEFAULT.skew_s)

        # evidence pool
        from tendermint_tpu.evidence.pool import EvidencePool

        self.evidence_pool = EvidencePool(new_db("memdb"), self.state_store,
                                          self.block_store, clock=self.clock)
        self.store_repairer.evidence_db = self.evidence_pool._db
        self.evidence_pool.on_corruption = self.store_repairer.note

        # block executor
        self.block_exec = BlockExecutor(
            self.state_store, self.proxy_app.consensus, mempool=self.mempool,
            evidence_pool=self.evidence_pool, event_bus=self.event_bus,
            block_store=self.block_store,
        )

        # consensus
        wal = WAL(config.wal_file()) if config.consensus.wal_path else None
        self.consensus = ConsensusState(
            config.consensus, state, self.block_exec, self.block_store,
            mempool=self.mempool, evidence_pool=self.evidence_pool,
            priv_validator=self.priv_validator, event_bus=self.event_bus, wal=wal,
            clock=self.clock,
        )
        if config.mempool.broadcast:
            self.mempool.enable_txs_available()

        # p2p
        self.node_key = node_key if node_key is not None else NodeKey.load_or_gen(
            config.node_key_file())
        node_info = NodeInfo(
            node_id=self.node_key.id(),
            network=self.genesis.chain_id,
            moniker=config.base.moniker,
        )
        self.transport = Transport(self.node_key, node_info,
                                   config.p2p.handshake_timeout_s,
                                   config.p2p.dial_timeout_s)
        # overload-resilience plane (utils/peerscore.py, docs/OVERLOAD.md):
        # per-node scoreboard + per-peer per-channel ingress ceilings
        from tendermint_tpu.utils import peerscore

        scoreboard = peerscore.PeerScoreBoard(
            peerscore.ScoreConfig.from_p2p_config(config.p2p), logger=logger)
        self.switch = Switch(self.transport, logger=logger,
                             max_inbound=config.p2p.max_num_inbound_peers,
                             max_outbound=config.p2p.max_num_outbound_peers,
                             send_rate=config.p2p.send_rate,
                             recv_rate=config.p2p.recv_rate,
                             scoreboard=scoreboard,
                             msg_rates=peerscore.parse_rate_spec(
                                 config.p2p.recv_msg_rate))
        # drain-bitmap invalid-signature attribution feeds the same board
        self.consensus.scoreboard = scoreboard

        # state sync runs only on a fresh node (reference: node.go:991
        # startStateSync is gated on state.LastBlockHeight == 0)
        self._statesync_active = (config.statesync.enable
                                  and state.last_block_height == 0)
        fast_sync = config.base.fast_sync_mode and len(self.genesis.validators) > 1
        wait_sync = fast_sync or self._statesync_active
        self.consensus_reactor = ConsensusReactor(self.consensus, wait_sync=wait_sync)
        self.mempool_reactor = MempoolReactor(self.mempool, broadcast=config.mempool.broadcast)

        from tendermint_tpu.evidence.reactor import EvidenceReactor
        from tendermint_tpu.statesync import StateSyncReactor, Syncer

        if config.fastsync.version == "v1":
            from tendermint_tpu.blockchain.v1 import BlockchainReactorV1 as _BCR
        elif config.fastsync.version == "v2":
            from tendermint_tpu.blockchain.v2 import BlockchainReactorV2 as _BCR
        else:
            from tendermint_tpu.blockchain.reactor import BlockchainReactor as _BCR
        self.bc_reactor = _BCR(
            state, self.block_exec, self.block_store, fast_sync,
            self.consensus_reactor)
        # BlockResponses feed the repairer's fetch waiters; the repairer's
        # own requests ride the same 0x40 wire protocol over this switch
        self.bc_reactor.repairer = self.store_repairer
        self.store_repairer.switch = self.switch
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)
        syncer = None
        if self._statesync_active:
            syncer = Syncer(
                self.proxy_app.snapshot, self._make_state_provider(),
                chunk_request_timeout_s=config.statesync.chunk_request_timeout_s,
                chunk_fetchers=config.statesync.chunk_fetchers,
                logger=logger)
            # app reject_senders verdicts score the sending peer
            syncer.scoreboard = self.switch.scoreboard
        # Reactor is registered unconditionally: every node SERVES snapshots
        # from its app (reference: node.go:839 statesync.NewReactor).
        self.statesync_reactor = StateSyncReactor(self.proxy_app.snapshot, syncer)

        self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
        self.switch.add_reactor("BLOCKCHAIN", self.bc_reactor)
        self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
        self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
        self.switch.add_reactor("STATESYNC", self.statesync_reactor)

        # tx/block indexer (reference: node/node.go:269-315 createAndStart
        # IndexerService)
        self.tx_indexer = None
        self.block_indexer = None
        self.indexer_service = None
        self.event_sink = None
        if config.tx_index.indexer == "kv":
            from tendermint_tpu.state.txindex import (
                BlockIndexer,
                IndexerService,
                TxIndexer,
            )

            idx_db = new_db(backend, os.path.join(dbdir, "tx_index.db")
                            if backend != "memdb" else None)
            self.tx_indexer = TxIndexer(idx_db)
            self.block_indexer = BlockIndexer(idx_db)
            self.tx_indexer.on_corruption = self.store_repairer.note
            self.block_indexer.on_corruption = self.store_repairer.note
            self.store_repairer.tx_indexer = self.tx_indexer
            self.store_repairer.block_indexer = self.block_indexer
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus, logger)
        elif config.tx_index.indexer == "psql":
            # Write-only SQL sink (reference: node/node.go:282-299 "psql");
            # tx/block search RPCs report unsupported, as upstream.
            from tendermint_tpu.state.sql_sink import SqlEventSink, connect
            from tendermint_tpu.state.txindex import IndexerService

            if not config.tx_index.psql_conn:
                raise ValueError(
                    "the psql indexer requires tx_index.psql_conn")
            sink = SqlEventSink(connect(config.tx_index.psql_conn),
                                self.genesis.chain_id)
            self.event_sink = sink
            self.tx_indexer = sink.tx_indexer()
            self.block_indexer = sink.block_indexer()
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus, logger)

        # Prometheus metrics (reference: node/node.go:118-132 MetricsProvider)
        self.metrics = None
        self.metrics_server = None
        if config.instrumentation.prometheus:
            from tendermint_tpu.utils import metrics as tmmetrics

            self.metrics = tmmetrics.NodeMetrics(
                tmmetrics.Registry(config.instrumentation.namespace))
            tmmetrics.GLOBAL_NODE_METRICS = self.metrics

        # PEX + addrbook (reference: node/node.go:872-889
        # createAddrBookAndSetOnSwitch + createPEXReactorAndAddToSwitch)
        self.addr_book = None
        self.pex_reactor = None
        if config.p2p.pex:
            from tendermint_tpu.p2p.addrbook import AddrBook
            from tendermint_tpu.p2p.pex_reactor import PexReactor

            self.addr_book = AddrBook(
                config.base.resolve(config.p2p.addr_book_file),
                strict=config.p2p.addr_book_strict)
            self.pex_reactor = PexReactor(
                self.addr_book, seed_mode=config.p2p.seed_mode,
                seeds=config.p2p.seeds.split(",") if config.p2p.seeds else [],
                logger=logger)
            self.switch.add_reactor("PEX", self.pex_reactor)
            # a ban evicts the peer from the address book too: PEX must
            # not keep recommending (or redialing) a sanctioned identity
            self.switch.scoreboard.on_ban.append(
                lambda pid, until: self.addr_book.mark_bad(pid))

        # flight recorder (utils/trace.py, docs/OBSERVABILITY.md): one
        # instance-scoped Tracer per node — the module-global ring would
        # interleave spans from every node of an in-process mesh. Enabled
        # by TMTPU_TRACE=1 (ring size TMTPU_TRACE_CAP); the fabric/soak
        # harness and the unsafe_trace RPC route can flip it live.
        from tendermint_tpu.utils import trace as tmtrace

        self.tracer = tmtrace.Tracer(name=self.node_key.id()[:12],
                                     enabled=tmtrace.trace_enabled_from_env())
        self.consensus.tracer = self.tracer
        self.mempool.tracer = self.tracer
        self.switch.tracer = self.tracer
        self.bc_reactor.tracer = self.tracer
        self.store_repairer.tracer = self.tracer

        self.rpc_server = None
        self._tx_notify_thread = None

        # consensus stall watchdog (consensus/watchdog.py): a node stalled
        # behind a healed partition hands itself back to fast-sync catchup
        from tendermint_tpu.consensus.watchdog import ConsensusWatchdog

        self.watchdog = ConsensusWatchdog(
            config.consensus, self.block_store, self.consensus_reactor,
            self.bc_reactor, self.handoff_to_fastsync,
            metrics=self.metrics, logger=logger)

    def install_misbehavior(self, spec: str) -> None:
        """Maverick mode: make THIS node byzantine (reference:
        test/maverick/consensus/misbehavior.go, selected per node via the
        maverick binary's --misbehaviors flag; here via the TMTPU_BYZ /
        TMTPU_MISBEHAVIOR env vars so an e2e manifest can mark a real
        PROCESS byzantine).

        ``spec`` is a consensus/misbehavior.py behavior spec — a bare
        behavior name (``double_prevote``) or a height-windowed map
        (``equivocate~3-5+lunatic~7-``, docs/BYZANTINE.md). The installer
        swaps a double-sign-guarded FilePV for an unguarded signer with
        the SAME key (a byzantine actor ignores its own safety guard) and
        wires the per-slot consensus hooks."""
        from tendermint_tpu.consensus import misbehavior as mb

        mb.install(self, spec)

    # --- lifecycle (reference: node/node.go:941 OnStart) -------------------

    def start(self) -> None:
        # Chaos layer: (re)load TMTPU_FAULTS/TMTPU_FAULT_SEED so every node
        # process starts its fault-site hit counters from zero -- a crash
        # matrix run is then replayable from the env spec + seed alone.
        from tendermint_tpu.utils import faults, nemesis

        faults.install_from_env()
        nemesis.install_from_env()
        # AOT-warm the batch-verify kernel off the critical path so the first
        # real commit at a warm bucket size is a compile-cache hit
        # (reference has no analogue; XLA compilation is TPU-build-specific).
        from tendermint_tpu.crypto import batch as crypto_batch

        crypto_batch.warmup()
        if self.config.p2p.laddr:
            la = self.transport.listen(self.config.p2p.laddr)
            if self.addr_book is not None:
                from tendermint_tpu.p2p.addrbook import NetAddress

                hp = la.split("://", 1)[1]
                host, port = hp.rsplit(":", 1)
                self.addr_book.add_our_address(
                    NetAddress(self.node_key.id(), host, int(port)))
        self.switch.start()
        # boot-time integrity scrub (TMTPU_SCRUB_ON_START=0 opts out,
        # docs/DURABILITY.md), on a background thread: the full walk is
        # O(chain length) and must not serialize startup. Serving paths
        # stay safe meanwhile — every read is individually checked, so a
        # peer asking for a not-yet-scrubbed rotten row gets typed-missing
        # and the repair hook fires. Repairs drain on the repairer's
        # background worker once peers connect.
        from tendermint_tpu.store.scrub import scrub_on_start_enabled

        if scrub_on_start_enabled():
            import threading

            def _boot_scrub():
                try:
                    report = self.scrubber().scrub(
                        repairer=self.store_repairer, drain=False)
                    if report.corruptions and self.logger:
                        self.logger.error(
                            "startup scrub found corruption; repairs "
                            "scheduled", corrupt=len(report.corruptions),
                            checked=report.checked)
                except Exception as e:  # noqa: BLE001 - the scrub is
                    # advisory; a failed pass must not take the node down
                    if self.logger:
                        self.logger.error("startup scrub failed", err=e)

            threading.Thread(target=_boot_scrub, name="boot-scrub",
                             daemon=True).start()
        if self.config.p2p.persistent_peers:
            self.switch.add_persistent_peers(
                self.config.p2p.persistent_peers.split(","))
        if self._statesync_active:
            import threading

            threading.Thread(target=self._run_state_sync, daemon=True).start()
        elif not self.consensus_reactor.wait_sync:
            self.consensus.start()
        else:
            self.bc_reactor.start_sync()
        self.watchdog.start()
        if self.mempool.txs_available() is not None:
            import threading

            def notify():
                ev = self.mempool.txs_available()
                try:
                    while self._running:
                        if ev.wait(timeout=0.2):
                            ev.clear()
                            self.consensus.handle_txs_available()
                except Exception as e:  # noqa: BLE001 - notifier death would
                    # silently stop empty-block-suppressed proposers
                    if self.logger:
                        self.logger.error("tx-available notifier crashed",
                                          err=e)

            self._running = True
            self._tx_notify_thread = threading.Thread(target=notify, daemon=True)
            self._tx_notify_thread.start()
        else:
            self._running = True
        # RPC
        if self.config.rpc.laddr:
            from tendermint_tpu.rpc.server import RPCServer

            self.rpc_server = RPCServer(self)
            self.rpc_server.start(self.config.rpc.laddr)
        if self.config.rpc.grpc_laddr:
            from tendermint_tpu.rpc.grpc_server import BroadcastAPIServer

            self.grpc_server = BroadcastAPIServer(self, self.config.rpc.grpc_laddr)
            self.grpc_server.start()
        # indexer + Prometheus (reference: node/node.go:964,1219)
        if self.indexer_service is not None:
            self.indexer_service.start()
        if self.metrics is not None:
            from tendermint_tpu.utils.metrics import MetricsServer

            self.metrics_server = MetricsServer(
                self.metrics.registry,
                self.config.instrumentation.prometheus_listen_addr)
            self.metrics_server.start()
            self._metrics_thread = __import__("threading").Thread(
                target=self._metrics_sampler, name="metrics-sampler", daemon=True)
            self._metrics_thread.start()

    def stop(self) -> None:
        self._running = False
        # release the flight recorder's module-wide ENABLED refcount: a
        # stopped node must not pin every later hot-path guard in this
        # process on the instrumented branch (fabric churn builds and
        # stops hundreds of nodes per session)
        self.tracer.disable()
        self.watchdog.stop()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop()
        if self.indexer_service is not None:
            self.indexer_service.stop()
        if self.event_sink is not None:
            self.event_sink.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.consensus.stop()
        # drain queued post-commit event publishes (so indexers/subscribers
        # see every committed height), then park the worker thread
        self.block_exec.flush_post_commit(timeout_s=5.0)
        self.block_exec.stop()
        self.switch.stop()
        if getattr(self, "signer_endpoint", None) is not None:
            self.signer_endpoint.close()
        # release the ingest coalescer's executor thread (it holds strong
        # mempool/app refs; fabric churn would otherwise leak one parked
        # thread per stopped node, docs/INGEST.md)
        self.mempool._ingest.stop()
        self.proxy_app.stop()

    def abort(self) -> None:
        """Power-loss teardown (docs/SOAK.md crash actions): release this
        incarnation's threads and sockets WITHOUT the orderly flushes
        stop() performs — no consensus stop (whose WAL close is preceded by
        completing the in-flight transition), no post-commit drain, no
        indexer join, no sink/DB close — so the durable home is abandoned
        exactly as the crash instant left it and a rebooted incarnation
        must recover through handshake + WAL replay + fast-sync alone.

        In-process honesty note: the hosting interpreter survives, so
        bytes already buffered by the OS (and sqlite connections reaped by
        GC) persist — a strict SUPERSET of what a real power cut keeps.
        Sub-fsync damage (a torn WAL tail) is injected explicitly by the
        crash harness on the abandoned home (faults.tear_wal_tail)."""
        self._running = False
        self.tracer.disable()
        self.watchdog.stop()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        # freeze consensus: pause() parks the receive routine and ticker
        # but leaves the WAL unclosed and any half-finalized round state
        # (e.g. a crash-site rule that aborted _finalize_commit) in place
        self.consensus.pause()
        if self.indexer_service is not None:
            # detach from the event bus without draining queued postings —
            # a crash loses exactly the not-yet-indexed tail
            self.indexer_service.stop()
        # park worker threads without flush_post_commit: queued event
        # publishes for already-applied heights are lost, as in a crash
        self.block_exec.stop()
        self.switch.stop()
        if getattr(self, "signer_endpoint", None) is not None:
            self.signer_endpoint.close()
        self.mempool._ingest.stop()
        self.proxy_app.stop()

    def _metrics_sampler(self) -> None:
        """Gauge sampling loop; histograms are fed at their call sites
        (reference wires metrics structs through constructors -- a sampler
        keeps the hot paths free of metric plumbing)."""
        import sys
        import time as _t

        from tendermint_tpu.utils import faults as _faults
        from tendermint_tpu.utils import nemesis as _nemesis

        m = self.metrics
        last_height = self.block_store.height
        last_height_t = _t.monotonic()
        # chaos counters are sampled as deltas against the layers' own
        # monotonic counts, so /metrics stays a true Prometheus counter
        last_site_hits: dict = {}
        last_fired: dict = {}
        last_nemesis_fired: dict = {}
        last_bans = 0
        last_shed: dict = {}
        last_rate_limited: dict = {}
        last_score_peers: set = set()
        # Counter series are permanent once created; cap the per-peer
        # label space so identity-minting churn cannot grow /metrics
        # without bound (overflow aggregates under peer="_overflow")
        rl_label_cap = 1024
        rl_labels_seen: set = set()

        def _rl_labels(k):
            peer = k[0][:16]
            if peer in rl_labels_seen or len(rl_labels_seen) < rl_label_cap:
                rl_labels_seen.add(peer)
                return {"peer": peer, "channel": k[1]}
            return {"peer": "_overflow", "channel": k[1]}

        def _pump_counter(counter, now_counts, last_counts, label_fn):
            for key, n in now_counts.items():
                delta = n - last_counts.get(key, 0)
                if delta > 0:
                    counter.add(delta, **label_fn(key))
            last_counts.clear()
            last_counts.update(now_counts)

        while self._running:
            try:
                h = self.block_store.height
                m.height.set(h)
                if h > last_height:
                    now = _t.monotonic()
                    m.block_interval_seconds.observe((now - last_height_t) / max(h - last_height, 1))
                    meta = self.block_store.load_block_meta(h)
                    if meta is not None:
                        m.num_txs.set(meta.num_txs)
                        m.total_txs.add(meta.num_txs)
                        m.block_size_bytes.set(meta.block_size)
                    last_height, last_height_t = h, now
                st = self.state_store.load()
                if st.validators is not None:
                    m.validators.set(st.validators.size())
                    m.validators_power.set(st.validators.total_voting_power())
                m.mempool_size.set(self.mempool.size())
                m.peers.set(len(self.switch.peers))
                m.rounds.set(getattr(self.consensus.rs, "round", 0))
                # chaos observability: fault-layer hit/fired counts and
                # nemesis link-plane firings, as counter deltas
                hits, fired = _faults.snapshot()
                _pump_counter(m.fault_site_hits, hits, last_site_hits,
                              lambda site: {"site": site})
                _pump_counter(m.faults_fired, fired, last_fired,
                              lambda k: {"site": k[0], "action": k[1]})
                _, nem_fired = _nemesis.PLANE.snapshot()
                _pump_counter(m.nemesis_fired, nem_fired, last_nemesis_fired,
                              lambda k: {"site": k[0], "action": k[1]})
                # overload-resilience plane: scores as live gauges, bans/
                # sheds/rate-limits as counter deltas (one board per node)
                board = self.switch.scoreboard.snapshot()
                score_peers = {pid[:16] for pid in board["scores"]}
                for pid, s in board["scores"].items():
                    m.peer_score.set(s, peer=pid[:16])
                for pid in last_score_peers - score_peers:
                    # banned/decayed-away peers: drop the series — a
                    # frozen pre-ban value misleads dashboards, and a
                    # zeroed-but-kept line per identity ever seen would
                    # grow /metrics cardinality without bound
                    m.peer_score.remove(peer=pid)
                last_score_peers = score_peers
                if board["bans_total"] > last_bans:
                    m.peers_banned.add(board["bans_total"] - last_bans)
                    last_bans = board["bans_total"]
                _pump_counter(m.shed, board["shed"], last_shed,
                              lambda ch: {"channel": ch})
                _pump_counter(m.rate_limited, board["rate_limited"],
                              last_rate_limited, _rl_labels)
                # device breaker state: only meaningful once a kernel
                # module is loaded; never force the import from a sampler
                for kernel in ("ed25519", "sr25519"):
                    kmod = sys.modules.get(f"tendermint_tpu.ops.{kernel}_batch")
                    if kmod is not None:
                        m.breaker_open.set(
                            1.0 if kmod.BREAKER.is_open else 0.0, kernel=kernel)
                        m.breaker_trips.set(kmod.BREAKER.trips, kernel=kernel)
            except Exception:  # noqa: BLE001 - sampling must never kill a node
                pass
            _t.sleep(0.25)

    # --- watchdog recovery -------------------------------------------------

    def handoff_to_fastsync(self) -> None:
        """Stall-watchdog recovery: pause the spinning consensus machine
        and re-enter fast-sync catchup — the block pool + verify-ahead
        pipeline pull the missing heights from peers' stored commits, then
        switch_to_consensus restarts consensus at the tip. No process
        restart, no WAL close; the consensus reactor's wait_sync latch
        keeps vote/proposal handling quiet while the pipeline owns the
        store."""
        self.consensus_reactor.wait_sync = True
        self.consensus.pause()
        self.consensus.rewind_for_catchup()
        self.bc_reactor.switch_to_fast_sync(self.state_store.load())

    # --- state sync --------------------------------------------------------

    def _make_state_provider(self):
        """Light-client state provider over the configured RPC servers
        (reference: node.go:648 startStateSync -> stateprovider.go:48)."""
        from tendermint_tpu.light.client import TrustOptions
        from tendermint_tpu.light.provider import HTTPProvider
        from tendermint_tpu.statesync import LightClientStateProvider

        cfg = self.config.statesync
        servers = [s for s in cfg.rpc_servers if s]
        if not servers:
            raise ValueError("state sync requires statesync.rpc_servers")
        if cfg.trust_height <= 0 or not cfg.trust_hash:
            raise ValueError("state sync requires statesync.trust_height and trust_hash")
        chain_id = self.genesis.chain_id
        providers = [HTTPProvider(chain_id, s) for s in servers]
        return LightClientStateProvider(
            chain_id,
            (self.genesis.consensus_params.version.app_version
             if self.genesis.consensus_params else 0),
            TrustOptions(period_s=cfg.trust_period_s, height=cfg.trust_height,
                         hash=bytes.fromhex(cfg.trust_hash)),
            providers[0], providers[1:],
            consensus_params=self.genesis.consensus_params,
            initial_height=self.genesis.initial_height,
            logger=self.logger,
        )

    def _run_state_sync(self) -> None:
        """Bootstrap from a snapshot, then hand off to fast sync (reference:
        node.go:991 startStateSync)."""
        cfg = self.config.statesync
        try:
            state, commit = self.statesync_reactor.sync(cfg.discovery_time_s)
        except Exception as e:  # noqa: BLE001
            if self.logger:
                self.logger.error("state sync failed", err=e)
            # Fall back to fast sync from genesis rather than hanging.
            self.bc_reactor.start_sync()
            return
        self.state_store.bootstrap(state)
        self.block_store.save_seen_commit(state.last_block_height, commit)
        # consensus picks the state up via the fast-sync -> consensus handoff
        # (ConsensusReactor.switch_to_consensus -> cs.update_to_state)
        self.bc_reactor.switch_to_fast_sync(state)

    # --- helpers -----------------------------------------------------------

    def scrubber(self):
        """A Scrubber over this node's full storage plane (startup pass +
        the ``unsafe_scrub`` RPC route; docs/DURABILITY.md)."""
        from tendermint_tpu.store.scrub import Scrubber

        idx_db = (self.tx_indexer._db
                  if getattr(self, "tx_indexer", None) is not None
                  and hasattr(self.tx_indexer, "_db") else None)
        return Scrubber(
            block_store=self.block_store, state_store=self.state_store,
            evidence_db=self.evidence_pool._db, txindex_db=idx_db,
            tracer=self.tracer)

    def p2p_addr(self) -> str:
        la = self.transport.node_info.listen_addr
        return f"{self.node_key.id()}@{la.split('://', 1)[1]}" if la else ""
