"""sr25519 (schnorrkel) keys — Schnorr over ristretto255 with merlin
transcripts (reference: crypto/sr25519/pubkey.go, privkey.go, which wrap
ChainSafe/go-schnorrkel).

Full from-scratch stack, spec-faithful:
 - keccak-f[1600] (FIPS 202) -> STROBE-128 (v1.0.2) -> merlin transcripts
 - ristretto255 encode/decode/equality (RFC 9496)
 - schnorrkel signing protocol: SigningContext transcript with EMPTY
   context label (reference privkey.go:34 NewSigningContext([]byte{}, msg)),
   proto "Schnorr-sig", challenge via 64-byte transcript PRF reduced mod L

Key-material semantics match the reference exactly: the stored 32-byte
private key is treated as a schnorrkel MINI secret and ExpandEd25519'd at
every use (privkey.go:27-33); pubkey = (clamped/8)*B ristretto-encoded;
Address = first 20 bytes of SHA-256 (pubkey.go:136, tmhash truncation —
unlike secp256k1's bitcoin-style address).

Signatures are VERIFY-compatible with go-schnorrkel in both directions;
byte-equality of signatures is not a goal (schnorrkel signing is randomized
— the witness nonce enters the transcript RNG).
"""

from __future__ import annotations

import hashlib
import os

from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.crypto import keys

KEY_TYPE = "sr25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 32
SIGNATURE_SIZE = 64

P = ed.P
L = ed.L
D = ed.D

# --- keccak-f[1600] ---------------------------------------------------------

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_KECCAK_ROT = [
    [0, 36, 3, 41, 18], [1, 44, 10, 45, 2], [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56], [27, 20, 39, 8, 14],
]
_M64 = (1 << 64) - 1


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (64 - n))) & _M64


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation of a 200-byte state."""
    a = [[int.from_bytes(state[8 * (x + 5 * y): 8 * (x + 5 * y) + 8], "little")
          for y in range(5)] for x in range(5)]
    for rc in _KECCAK_RC:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _KECCAK_ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y] & _M64) & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= rc
    for x in range(5):
        for y in range(5):
            state[8 * (x + 5 * y): 8 * (x + 5 * y) + 8] = a[x][y].to_bytes(8, "little")


# --- STROBE-128 (v1.0.2, merlin subset: meta-AD / AD / PRF / KEY) -----------

_STROBE_R = 166
_FLAG_I, _FLAG_A, _FLAG_C, _FLAG_M, _FLAG_K = 1, 2, 4, 16, 32


class Strobe128:
    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[:6] = bytes([1, _STROBE_R + 2, 1, 0, 1, 96])
        st[6:18] = b"STROBEv1.0.2"
        keccak_f1600(st)
        self.state = st
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def clone(self) -> "Strobe128":
        c = Strobe128.__new__(Strobe128)
        c.state = bytearray(self.state)
        c.pos, c.pos_begin, c.cur_flags = self.pos, self.pos_begin, self.cur_flags
        return c

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for b in data:
            self.state[self.pos] ^= b
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        for b in data:
            self.state[self.pos] = b
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.state[self.pos])
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError("flag mismatch on continued operation")
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if flags & (_FLAG_C | _FLAG_K) and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        self._overwrite(data)


# --- merlin transcript ------------------------------------------------------


def _le32(n: int) -> bytes:
    return n.to_bytes(4, "little")


class Transcript:
    def __init__(self, label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        t = Transcript.__new__(Transcript)
        t.strobe = self.strobe.clone()
        return t

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label + _le32(len(message)))
        self.strobe.ad(message)

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label + _le32(n))
        return self.strobe.prf(n)

    def challenge_scalar(self, label: bytes) -> int:
        return int.from_bytes(self.challenge_bytes(label, 64), "little") % L

    def witness_scalar(self, label: bytes, witness: bytes,
                       rng_seed: bytes | None = None) -> int:
        """merlin TranscriptRng: clone, rekey with the witness, key with
        (normally OS) randomness, squeeze a wide scalar."""
        s = self.strobe.clone()
        s.meta_ad(label + _le32(len(witness)))
        s.key(witness)
        seed = rng_seed if rng_seed is not None else os.urandom(32)
        s.meta_ad(b"rng" + _le32(len(seed)))
        s.key(seed)
        s.meta_ad(b"" + _le32(64))
        return int.from_bytes(s.prf(64), "little") % L


# --- ristretto255 (RFC 9496) ------------------------------------------------

SQRT_M1 = pow(2, (P - 1) // 4, P)
_A_MINUS_D = (-1 - D) % P


def _is_neg(x: int) -> bool:
    return (x % P) & 1 == 1


def _ct_abs(x: int) -> int:
    x %= P
    return P - x if _is_neg(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """RFC 9496 4.2 SQRT_RATIO_M1."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    correct = check == u % P
    flipped = check == (-u) % P
    flipped_i = check == (-u) % P * SQRT_M1 % P
    if flipped or flipped_i:
        r = r * SQRT_M1 % P
    return (correct or flipped), _ct_abs(r)


_ok, INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, _A_MINUS_D)
assert _ok


def ristretto_decode(data: bytes):
    """32 bytes -> extended point (x, y, z=1, t) or None."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    if s >= P or _is_neg(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _ct_abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_neg(t) or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_encode(pt) -> bytes:
    """Extended (X, Y, Z, T) -> canonical 32 bytes (RFC 9496 4.3.2)."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) % P * ((z0 - y0) % P) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix = x0 * SQRT_M1 % P
    iy = y0 * SQRT_M1 % P
    enchanted = den1 * INVSQRT_A_MINUS_D % P
    rotate = _is_neg(t0 * z_inv % P)
    if rotate:
        x, y, den_inv = iy, ix, enchanted
    else:
        x, y, den_inv = x0, y0, den2
    if _is_neg(x * z_inv % P):
        y = (-y) % P
    s = _ct_abs(den_inv * ((z0 - y) % P) % P)
    return s.to_bytes(32, "little")


def ristretto_eq(p, q) -> bool:
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (x1 * y2 - y1 * x2) % P == 0 or (y1 * y2 - x1 * x2) % P == 0


def _pt_scalarmult(k: int, pt):
    return ed._scalarmult(k, pt)


def _pt_add(p, q):
    return ed._add(p, q)


# --- schnorrkel protocol ----------------------------------------------------


def _signing_context(msg: bytes) -> Transcript:
    """reference privkey.go:34: NewSigningContext([]byte{}, msg)."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    return t


def _expand_ed25519(mini: bytes) -> tuple[int, bytes]:
    """MiniSecretKey.ExpandEd25519: (key scalar = clamped/8, 32-byte nonce)."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3  # divide by cofactor
    return scalar, h[32:]


def pubkey_from_mini(mini: bytes) -> bytes:
    scalar, _ = _expand_ed25519(mini)
    return ristretto_encode(_pt_scalarmult(scalar, ed.BASE))


def sign(mini: bytes, msg: bytes, rng_seed: bytes | None = None) -> bytes:
    scalar, nonce = _expand_ed25519(mini)
    pub = ristretto_encode(_pt_scalarmult(scalar, ed.BASE))
    t = _signing_context(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    r = t.witness_scalar(b"signing", nonce, rng_seed)
    R = _pt_scalarmult(r, ed.BASE)
    r_bytes = ristretto_encode(R)
    t.append_message(b"sign:R", r_bytes)
    k = t.challenge_scalar(b"sign:c")
    s = (k * scalar + r) % L
    sig = bytearray(r_bytes + s.to_bytes(32, "little"))
    sig[63] |= 128  # schnorrkel v1 marker bit
    return bytes(sig)


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != SIGNATURE_SIZE or len(pub) != PUBKEY_SIZE:
        return False
    if sig[63] & 128 == 0:
        return False  # not schnorrkel-marked (reference Signature.Decode)
    a_pt = ristretto_decode(pub)
    r_pt = ristretto_decode(sig[:32])
    if a_pt is None or r_pt is None:
        return False
    s_bytes = bytearray(sig[32:])
    s_bytes[31] &= 127
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return False  # non-canonical scalar
    t = _signing_context(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    t.append_message(b"sign:R", sig[:32])
    k = t.challenge_scalar(b"sign:c")
    # s*B == R + k*A
    lhs = _pt_scalarmult(s, ed.BASE)
    rhs = _pt_add(r_pt, _pt_scalarmult(k, a_pt))
    return ristretto_eq(lhs, rhs)


# --- key classes ------------------------------------------------------------


class PubKey(keys.PubKey):
    def __init__(self, data: bytes):
        self.data = bytes(data)

    @property
    def type(self) -> str:
        return KEY_TYPE

    def address(self) -> bytes:
        """SHA256-20 truncation (reference: pubkey.go:136)."""
        return hashlib.sha256(self.data).digest()[:20]

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # C host fast path (curve + strobe challenge in C); `verify()` above
        # stays the pure-Python reference for differential tests.
        from tendermint_tpu.ops import chost

        if chost.available():
            return chost.sr25519_verify_one(self.data, msg, sig)
        return verify(self.data, msg, sig)

    def equals(self, other) -> bool:
        return isinstance(other, PubKey) and self.data == other.data

    def __repr__(self) -> str:
        return f"PubKeySr25519{{{self.data.hex().upper()}}}"


class PrivKey(keys.PrivKey):
    """The 32 bytes are a schnorrkel mini secret (see module docstring)."""

    def __init__(self, data: bytes):
        if len(data) != PRIVKEY_SIZE:
            raise ValueError("sr25519 private key must be 32 bytes")
        self.data = bytes(data)

    @property
    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        return sign(self.data, msg)

    def pub_key(self) -> PubKey:
        return PubKey(pubkey_from_mini(self.data))

    def equals(self, other) -> bool:
        import hmac

        return isinstance(other, PrivKey) and hmac.compare_digest(self.data, other.data)


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    """reference: privkey.go:104 GenPrivKeyFromSecret (SHA-256 of secret)."""
    if seed is None:
        return PrivKey(os.urandom(32))
    return PrivKey(hashlib.sha256(seed).digest())
