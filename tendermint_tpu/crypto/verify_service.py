"""Continuous-batching verify service: ONE device-owning executor for all
signature-verification traffic (ROADMAP item 1).

BENCH r05: the headline 20,480-sig commit verify is floor-bound — of the
151 ms p50, ~104 ms is the fixed host<->device round trip
(`sync_floor_ms`), paid once per DECISION no matter how the kernel
improves. Verify-ahead (blockchain/pipeline.py) and the batched readback
(crypto/batch.prefetch) only amortize that floor across decisions ONE
CALLER already has in flight; nothing shares it across CALLERS. A 50-node
fabric, a consensus drain racing a fast-sync burst, or light range chunks
each pay their own floor.

This module applies the inference-serving fix — continuous batching — to
the verify plane:

 * every kernel-worthy ``BatchVerifier.dispatch()`` (the consensus vote
   drain, fast-sync verify-ahead, light ``range_verify``, statesync via the
   light client — the whole registry in crypto/batch.py) submits its items
   to one process-wide :class:`VerifyService` and gets back a
   ``ServicePending`` with unchanged PendingVerify semantics;
 * a dedicated executor thread COALESCES requests arriving within a short
   window (``TMTPU_VERIFY_WINDOW_US``) into one shared kernel launch per
   key type — N concurrent dispatches pay ONE sync floor;
 * generations are DOUBLE-BUFFERED: while generation k's kernel computes
   and its D2H copy flies (copy_to_host_async starts at dispatch), the
   executor host-preps and dispatches generation k+1, and only then blocks
   on k's readback;
 * the launch goes through the SAME ``ops.*.dispatch_batch`` the callers
   used directly — host-crossover routing, multi-device sharding
   (parallel/batch_shard.should_shard on the COALESCED size), the
   ``ops.*.device`` fault sites, and the circuit breaker all apply
   unchanged, so bitmaps are byte-identical and a device failure
   mid-coalesce degrades to the host fallback with every waiter resolved
   exactly once;
 * hot validator KeySets stay device-resident across heights and across
   interleavings via the unique-key-set LRU in ops/ed25519_batch
   (build_keyset level 2): a coalesced launch's novel pubkey interleaving
   reuses the cached comb tables, paying only the O(n) index mapping;
 * the single blocking readback point is :func:`_readback` (audited by the
   tmlint ``device-sync-choke-point`` rule, and routed through
   crypto/batch._device_get so the perf-gate fetch spy still counts it);
 * queue/launch/readback/replay spans are recorded on the DISPATCHING
   node's tracer (each request captures utils/trace.current() at submit),
   so flight-recorder phase attribution stays per-node-accurate.

Knobs (docs/CONFIG.md): ``TMTPU_VERIFY_SERVICE=0`` restores direct
per-caller dispatch; ``TMTPU_VERIFY_WINDOW_US`` sets the coalescing window
(default 150); ``TMTPU_VERIFY_MAX_BATCH`` caps the items per shared launch
(default 65536).
"""

from __future__ import annotations

import importlib
import os
import queue
import threading
import time as _time

from tendermint_tpu.crypto import batch as _batch
from tendermint_tpu.utils import trace as _trace

_OPS_MODULES = {
    "ed25519": "tendermint_tpu.ops.ed25519_batch",
    "sr25519": "tendermint_tpu.ops.sr25519_batch",
}


def enabled() -> bool:
    """False only when the operator opted out (TMTPU_VERIFY_SERVICE=0;
    read per dispatch so tests and the concurrent_verify bench can flip it
    without restarting)."""
    return os.environ.get("TMTPU_VERIFY_SERVICE") != "0"


def force_all() -> bool:
    """TMTPU_VERIFY_SERVICE=1: route EVERY kernel-worthy dispatch through
    the service, including sub-crossover host batches (tests, the graft
    stage, and the concurrent_verify bench use this to make coalescing
    deterministic)."""
    return os.environ.get("TMTPU_VERIFY_SERVICE") == "1"


def device_bound(n: int, force_device: bool) -> bool:
    """Would a direct dispatch of n items take the DEVICE route — i.e. pay
    the host<->device sync floor the service exists to share? Sub-crossover
    batches with the C host verifier present verify inline with NO floor;
    routing those through the executor buys nothing and costs a thread hop
    plus the coalescing window per flush — at 50-node-fabric scale (tiny
    vote drains, thousands of threads on one core) that serialization
    point measurably stalls consensus. So by default the service owns
    exactly the floor-paying traffic."""
    if force_device:
        return True
    from tendermint_tpu.ops import ed25519_batch

    if n >= ed25519_batch.host_crossover():
        return True
    from tendermint_tpu.ops import chost

    if not chost.available() and not chost.building():
        # no C host verifier: ops routes kernel-worthy batches to the
        # device at any size, so they pay the floor and should share it
        return True
    from tendermint_tpu.parallel import batch_shard

    return batch_shard.should_shard(n)


def window_us(default: int = 150) -> int:
    """Coalescing window: how long the executor waits for more dispatches
    after the first before launching. Latency cost for a lone caller; the
    price of sharing the floor for concurrent ones. TMTPU_VERIFY_WINDOW_US
    overrides."""
    v = os.environ.get("TMTPU_VERIFY_WINDOW_US")
    try:
        return max(0, int(v)) if v else default
    except ValueError:
        return default


def max_batch(default: int = 65536) -> int:
    """Item cap per shared launch (bounds worst-case host-prep latency and
    device memory of one generation). TMTPU_VERIFY_MAX_BATCH overrides."""
    v = os.environ.get("TMTPU_VERIFY_MAX_BATCH")
    try:
        return max(1, int(v)) if v else default
    except ValueError:
        return default


def _readback(tree):
    """THE service's single blocking D2H point (tmlint
    device-sync-choke-point audited site). Routed through
    crypto/batch._device_get so every blocking fetch in the process still
    funnels through one instrumented choke (and the perf-gate fetch spy
    counts the service's readbacks too)."""
    return _batch._device_get(tree)


def _safe_record(tracer, name: str, duration_s: float, **tags) -> None:
    """Flight-recorder writes from the executor must never be able to
    strand a generation's waiters: a tracer/metric-mirror failure is
    swallowed (the span is lost, the verification is not)."""
    try:
        tracer.record(name, duration_s, **tags)
    except Exception:  # noqa: BLE001 - observability never blocks resolution
        pass


class _Request:
    """One caller's dispatch: items of one key type, a completion event the
    waiter's ServicePending blocks on, and the flight-recorder context
    captured on the submitting thread."""

    __slots__ = ("kind", "items", "force_device", "done", "result", "error",
                 "tracer", "t_submit", "height")

    def __init__(self, kind, items, force_device):
        self.kind = kind
        self.items = items
        self.force_device = force_device
        self.done = threading.Event()
        self.result: tuple[bool, list[bool]] | None = None
        self.error: BaseException | None = None
        self.tracer = None
        self.t_submit = 0.0
        self.height = None


class VerifyService:
    """The device-owning executor. One per process (see :func:`get`)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._thread_mtx = threading.Lock()
        # observability counters (read by bench.py concurrent_verify and
        # the service tests; plain ints — the GIL makes += atomic enough
        # for monitoring)
        self.launches = 0            # shared kernel/host launches issued
        self.requests = 0            # dispatches submitted
        self.coalesced_items = 0     # items across all launches
        self.max_coalesced = 0       # most requests sharing one generation
        self.fallbacks = 0           # generations resolved via scalar floor

    # --- submission (any thread) -------------------------------------------

    def submit(self, kind: str, items, force_device: bool = False):
        """Queue one verify request; returns the caller's ServicePending.
        Never blocks beyond the queue put."""
        req = _Request(kind, items, force_device)
        if _trace.ENABLED:
            tr = _trace.current()
            if tr.enabled:
                req.tracer = tr
                req.height = tr.current_height()
        req.t_submit = _time.monotonic()
        self.requests += 1
        self._ensure_thread()
        self._q.put(req)
        return _batch.ServicePending(req)

    def _ensure_thread(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._thread_mtx:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="verify-service", daemon=True)
                self._thread.start()

    # --- executor loop ------------------------------------------------------

    def _run(self) -> None:
        gen = None  # the in-flight (dispatched, unfetched) generation
        while True:
            try:
                if gen is None:
                    first = self._q.get()
                    gen = self._dispatch(self._collect(first))
                # Double-buffer: while generation k computes (its D2H copy
                # started at dispatch), host-prep and dispatch k+1; only
                # then block on k's readback.
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    self._complete(gen)
                    gen = None
                    continue
                gen2 = self._dispatch(self._collect(nxt))
                self._complete(gen)
                gen = gen2
            except Exception as e:  # noqa: BLE001 - executor must never die
                # Anything that slipped past the per-generation fallbacks
                # (dispatch/complete/launch resolve their own requests on
                # failure). The in-flight generation's waiters MUST still
                # resolve — a stranded done-event is a silent node stall.
                if gen is not None:
                    for (_kind, mod, greqs, _items, _dev, _finish) in gen:
                        try:
                            self._resolve_scalar(mod, greqs)
                        except Exception:  # noqa: BLE001 - last resort
                            self._resolve_error(greqs, e)
                    gen = None
                continue

    def _collect(self, first: _Request) -> list[_Request]:
        """The continuous-batching step: drain requests arriving within the
        coalescing window (or already queued) into one generation, bounded
        by max_batch items."""
        reqs = [first]
        n = len(first.items)
        cap = max_batch()
        deadline = _time.monotonic() + window_us() / 1e6
        while n < cap:
            remaining = deadline - _time.monotonic()
            try:
                r = (self._q.get(timeout=remaining) if remaining > 0
                     else self._q.get_nowait())
            except queue.Empty:
                break
            reqs.append(r)
            n += len(r.items)
        return reqs

    def _dispatch(self, reqs: list[_Request]):
        """Group a generation by key type and issue one shared
        ops.dispatch_batch per kind (host prep + device dispatch, nothing
        fetched). Returns the in-flight generation for _complete()."""
        t0 = _time.monotonic()
        for r in reqs:
            if r.tracer is not None:
                _safe_record(r.tracer, "verify.queue", t0 - r.t_submit,
                             **({} if r.height is None
                                else {"height": r.height}))
        groups: dict[str, list[_Request]] = {}
        for r in reqs:
            groups.setdefault(r.kind, []).append(r)
        gen = []
        for kind, greqs in groups.items():
            gen.append(self._launch(kind, greqs))
        return [g for g in gen if g is not None]

    def _launch(self, kind: str, greqs: list[_Request]):
        items = [it for r in greqs for it in r.items]
        force = any(r.force_device for r in greqs)
        try:
            mod = importlib.import_module(_OPS_MODULES[kind])
        except Exception as e:  # noqa: BLE001 - unknown kind / import failure
            self._resolve_error(greqs, e)
            return None
        t0 = _time.monotonic()
        try:
            # Same entry the callers used directly: crossover routing,
            # sharding on the COALESCED size, ops.*.device fault site, and
            # the circuit breaker (a dispatch-time device failure already
            # comes back as the host fallback's (None, finish)).
            dev, finish = mod.dispatch_batch(items, force_device=force)
        except Exception:  # noqa: BLE001 - belt and braces under the breaker
            self._resolve_scalar(mod, greqs)
            return None
        prep_s = _time.monotonic() - t0
        self.launches += 1
        self.coalesced_items += len(items)
        self.max_coalesced = max(self.max_coalesced, len(greqs))
        for tr, height in self._unique_tracers(greqs):
            tags = {} if height is None else {"height": height}
            _safe_record(tr, "verify.host_prep", prep_s,
                         coalesced=len(greqs), sigs=len(items), **tags)
            _safe_record(tr, "verify.coalesce", 0.0, kind=kind,
                         requests=len(greqs), sigs=len(items), **tags)
        return (kind, mod, greqs, items, dev, finish)

    def _complete(self, gen) -> None:
        """Readback + per-request replay of one in-flight generation: ONE
        blocking fetch per kind, then slice each request's bitmap and set
        its completion event. Fetch-time device failures degrade through
        the kind's breaker to the host fallback; every waiter resolves
        exactly once on every path."""
        for kind, mod, greqs, items, dev, finish in gen:
            t0 = _time.monotonic()
            fetched = None
            if dev is not None:
                try:
                    fetched = _readback(dev)
                except Exception as e:  # noqa: BLE001 - dead device at fetch
                    mod.BREAKER.record_failure(e)
                    try:
                        dev, finish = mod._host_fallback(items, len(items))
                        fetched = None
                    except Exception:  # noqa: BLE001
                        self._resolve_scalar(mod, greqs)
                        continue
            t1 = _time.monotonic()
            try:
                bitmap = finish(fetched)
            except Exception:  # noqa: BLE001 - finish_cb already fell back
                self._resolve_scalar(mod, greqs)
                continue
            off = 0
            for r in greqs:
                n = len(r.items)
                lanes = [bool(b) for b in bitmap[off:off + n]]
                off += n
                r.result = (all(lanes), lanes)
            t2 = _time.monotonic()
            self._observe(greqs, t2)
            for tr, height in self._unique_tracers(greqs):
                tags = {} if height is None else {"height": height}
                _safe_record(tr, "verify.readback", t1 - t0,
                             coalesced=len(greqs), **tags)
                _safe_record(tr, "verify.replay", t2 - t1,
                             coalesced=len(greqs), **tags)
            # wake waiters LAST: a woken caller immediately contends for
            # the GIL, which would otherwise inflate the replay span with
            # the callers' own post-resolve work
            for r in greqs:
                r.done.set()

    # --- degradation floors -------------------------------------------------

    def _resolve_scalar(self, mod, greqs: list[_Request]) -> None:
        """Last-rung fallback: resolve every waiter via the kind's host
        fallback (C verifier when loaded, else the pure-Python scalar
        loop). Never raises into the executor loop; a request whose scalar
        replay itself fails gets the error (resolve() re-raises it on the
        WAITER's thread, where callers already have serial fallbacks)."""
        self.fallbacks += 1
        for r in greqs:
            if r.done.is_set():
                continue
            try:
                _, fb = mod._host_fallback(r.items, len(r.items))
                lanes = [bool(b) for b in fb(None)]
                r.result = (all(lanes), lanes)
            except Exception as e:  # noqa: BLE001
                r.error = e
            r.done.set()

    def _resolve_error(self, greqs: list[_Request], e: BaseException) -> None:
        for r in greqs:
            if not r.done.is_set():
                r.error = e
                r.done.set()

    # --- helpers ------------------------------------------------------------

    @staticmethod
    def _unique_tracers(greqs):
        """(tracer, height) per distinct dispatching tracer: shared-phase
        durations are recorded ONCE per node per generation, so a node with
        several requests in one launch doesn't double-count the shared
        prep/readback in its phase attribution."""
        seen = {}
        for r in greqs:
            if r.tracer is not None and id(r.tracer) not in seen:
                seen[id(r.tracer)] = (r.tracer, r.height)
        return seen.values()

    def _observe(self, greqs, t_done: float) -> None:
        """Per-REQUEST metrics, preserving the direct path's semantics:
        batch_verify_seconds spans dispatch(submit)->resolved — host prep,
        coalescing window, queue, device, and readback included — so the
        histogram's meaning does not silently change with the service on."""
        try:
            from tendermint_tpu.utils import metrics as tmmetrics

            m = tmmetrics.GLOBAL_NODE_METRICS
            if m is None:
                return
            for r in greqs:
                m.batch_verify_seconds.observe(t_done - r.t_submit)
                m.batch_verify_sigs.add(len(r.items))
        except Exception:  # noqa: BLE001 - metrics must not strand waiters
            pass


_SERVICE: VerifyService | None = None
_SERVICE_LOCK = threading.Lock()


def get() -> VerifyService:
    """The process-wide service (lazy; the executor thread starts on first
    submit)."""
    global _SERVICE
    s = _SERVICE
    if s is None:
        with _SERVICE_LOCK:
            if _SERVICE is None:
                _SERVICE = VerifyService()
            s = _SERVICE
    return s


def reset() -> None:
    """Tests: drop the singleton (a fresh one spins up on next submit; the
    old executor thread drains its queue and then idles forever — daemon,
    so it never blocks teardown)."""
    global _SERVICE
    with _SERVICE_LOCK:
        _SERVICE = None
