"""BatchVerifier: the pluggable batch signature-verification registry.

THE capability the reference lacks entirely (SURVEY.md: v0.34 has no
BatchVerifier interface; every verify path is a serial loop over
crypto.PubKey.VerifySignature, reference crypto/crypto.go:22-28). This module
introduces it: callers accumulate (pubkey, msg, sig) triples and flush them in
one call, which on TPU becomes a single wide Edwards-curve kernel launch
(tendermint_tpu.ops.ed25519_batch).

Semantics contract: `verify()` returns a per-item bitmap whose entries are
byte-identical to what the scalar `pub_key.verify_signature` path returns for
the same item. Callers that need the reference's serial early-exit/error-
attribution behavior (e.g. ValidatorSet.VerifyCommitLight) replay the serial
decision procedure over the bitmap -- verification is batched, the consensus
semantics are not changed.

Deferred contract: `dispatch()` issues all host prep + device work and
returns a :class:`PendingVerify` handle; `PendingVerify.resolve()` performs
the blocking device readback (if any) and returns the same (all_ok, bitmap)
pair `verify()` would. The host<->device round trip of this rig is
latency-bound (~100 ms floor per fetch regardless of batch size), so the
whole point of the split is that callers with SEVERAL decisions in flight
(fast-sync verify-ahead, light range sync, the consensus vote drain) fetch
them in one `jax.device_get` via :func:`prefetch` / :func:`resolve_all`
instead of paying one floor per decision.
"""

from __future__ import annotations

import abc
import os
import time as _time

from tendermint_tpu.crypto import keys
from tendermint_tpu.utils import trace as _trace


def _device_get(tree):
    """THE choke point for blocking D2H readbacks of the deferred verify
    API. Every PendingVerify fetch funnels through here so (a) prefetch can
    batch several pendings' outputs into one call and (b) tests can count
    blocking fetches with a spy (tests/test_perf_gate.py)."""
    import jax

    return jax.device_get(tree)


class PendingVerify:
    """A dispatched-but-unfetched batch verification.

    ``devs`` is the list of device outputs still in flight (None entries are
    sub-batches that already resolved on host); ``resolve_fn(fetched)`` --
    with ``fetched`` parallel to ``devs`` -- replays the per-item bitmap.
    ``resolve()`` is idempotent: the first call fetches and caches, later
    calls return the cached (all_ok, bitmap). ``children`` are sub-handles
    (MixedBatchVerifier's per-key-type pendings, which may be
    service-backed) whose in-flight state counts toward
    has_device_output()."""

    __slots__ = ("_devs", "_resolve", "_result", "_tracer", "_t_disp",
                 "_t_height", "_children")

    def __init__(self, devs, resolve_fn, children=()):
        self._devs = list(devs)
        self._resolve = resolve_fn
        self._result: tuple[bool, list[bool]] | None = None
        self._children = tuple(children)
        # flight-recorder context captured at dispatch (utils/trace.py):
        # the dispatching node's tracer, the dispatch timestamp (queue-wait
        # phase = resolve start - dispatch end), and the height context so
        # phases land on the right timeline even when resolve happens later
        self._tracer = None
        self._t_disp = 0.0
        self._t_height = None

    @property
    def resolved(self) -> bool:
        return self._result is not None

    def _devs_pending(self) -> bool:
        """Unfetched device buffers of THIS handle (children excluded):
        exactly the condition under which a _device_get is warranted."""
        return self._result is None and any(d is not None for d in self._devs)

    def has_device_output(self) -> bool:
        """True when resolve() will block — on a device fetch, or on a
        service-backed child whose shared launch is still in flight."""
        if self._result is not None:
            return False
        return (self._devs_pending()
                or any(c.has_device_output() for c in self._children))

    def _finish(self, fetched) -> None:
        self._result = self._resolve(fetched)
        # release device buffers (and the resolve closure's captures)
        self._devs = [None] * len(self._devs)
        self._resolve = None

    def _trace_tags(self) -> dict:
        return {} if self._t_height is None else {"height": self._t_height}

    def resolve(self) -> tuple[bool, list[bool]]:
        """Fetch (one _device_get when device outputs are pending) and
        return (all_ok, bitmap)."""
        if self._result is None:
            tr = self._tracer
            if tr is not None and tr.enabled:
                tags = self._trace_tags()
                if self._t_disp:
                    tr.record("verify.queue",
                              _time.monotonic() - self._t_disp, **tags)
                # _devs_pending, NOT has_device_output: a handle whose only
                # in-flight work is service-backed children has nothing to
                # fetch itself — a _device_get here would be a pointless
                # trip through the audited choke (and a phantom count on
                # the perf-gate fetch spy)
                if self._devs_pending():
                    with tr.span("verify.readback", **tags):
                        fetched = _device_get(self._devs)
                else:
                    fetched = self._devs
                with tr.span("verify.replay", **tags):
                    self._finish(fetched)
            else:
                fetched = (_device_get(self._devs) if self._devs_pending()
                           else self._devs)
                self._finish(fetched)
        return self._result


class ServicePending(PendingVerify):
    """A dispatch routed through the continuous-batching verify service
    (crypto/verify_service.py). The service executor owns host prep, the
    shared (coalesced) kernel launch, and the single batched readback;
    resolve() therefore waits on the request's completion event instead of
    fetching device buffers itself. Exactly-once: the executor resolves
    every request exactly once (result or error), and resolve() caches."""

    __slots__ = ("_req",)

    def __init__(self, req):
        super().__init__([], None)
        self._req = req

    def has_device_output(self) -> bool:
        """True while the shared launch is still in flight (resolve() would
        block on the service), so async callers (the vote drain, the
        verify-ahead pipeline) keep overlapping exactly as they do with a
        raw device handle."""
        return self._result is None and not self._req.done.is_set()

    def _finish(self, _fetched) -> None:
        req = self._req
        req.done.wait()
        if req.error is not None:
            raise req.error
        self._result = req.result
        self._req = None
        self._resolve = None
        self._devs = []

    def resolve(self) -> tuple[bool, list[bool]]:
        if self._result is None:
            self._finish(None)
        return self._result


def prefetch(pendings) -> None:
    """Fetch every unresolved pending's device outputs in ONE _device_get.

    The tunnel round trip is latency-bound: K sequential resolves cost K
    floors, one batched fetch costs one. Results are cached on each handle,
    so the later in-order resolve() calls return instantly. Host-resolved
    pendings are untouched. Service-backed pendings (ServicePending) carry
    no device outputs of their own — the verify service already coalesces
    their readbacks into its single fetch point — so they are simply
    waited on."""
    unres = [p for p in pendings if p.has_device_output()]
    svc = [p for p in unres if not p._devs_pending()]
    unres = [p for p in unres if p._devs_pending()]
    for p in svc:
        p.resolve()
    if not unres:
        return
    if _trace.ENABLED:
        tr = _trace.current()
        if tr.enabled:
            now = _time.monotonic()
            for p in unres:
                if p._t_disp:
                    pt = p._tracer if p._tracer is not None else tr
                    pt.record("verify.queue", now - p._t_disp,
                              **p._trace_tags())
            with tr.span("verify.readback", batched=len(unres)):
                fetched = _device_get([p._devs for p in unres])
            with tr.span("verify.replay", batched=len(unres)):
                for p, f in zip(unres, fetched):
                    p._finish(f)
            return
    fetched = _device_get([p._devs for p in unres])
    for p, f in zip(unres, fetched):
        p._finish(f)


def resolve_all(pendings) -> list[tuple[bool, list[bool]]]:
    """prefetch() + in-order resolve() of every handle."""
    prefetch(pendings)
    return [p.resolve() for p in pendings]


class BatchVerifier(abc.ABC):
    @abc.abstractmethod
    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        """Queue one (pubkey, message, signature) item."""

    @abc.abstractmethod
    def verify(self) -> tuple[bool, list[bool]]:
        """Verify everything queued. Returns (all_ok, per-item bitmap) and
        resets the queue."""

    def dispatch(self, force_device: bool = False) -> PendingVerify:
        """Issue host prep + device dispatch without fetching; resets the
        queue. Default (scalar) implementation verifies eagerly and returns
        an already-resolved handle."""
        res = self.verify()
        p = PendingVerify([None], None)
        p._result = res
        return p

    @abc.abstractmethod
    def __len__(self) -> int: ...


class ScalarBatchVerifier(BatchVerifier):
    """Fallback: the reference's serial loop, for key types without a batch
    kernel (and for differential testing)."""

    def __init__(self) -> None:
        self._items: list[tuple[keys.PubKey, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        out = [pk.verify_signature(m, s) for (pk, m, s) in self._items]
        self._items = []
        return all(out), out

    def __len__(self) -> int:
        return len(self._items)


def batch_min(default: int = 32) -> int:
    """Batch-size threshold below which the kernel is never launched.

    A 1-vote commit (single-validator chains, gossiped singles) must not pay
    kernel dispatch -- and on a cold process must not pay XLA compilation.
    The crossover depends on the SCALAR path's speed, so each verifier
    passes its own default: ed25519's scalar path is ~1-3 ms/sig (crossover
    in the tens of sigs), sr25519's is pure Python at ~18 ms/sig (crossover
    ~8). TM_TPU_BATCH_MIN overrides both."""
    v = os.environ.get("TM_TPU_BATCH_MIN")
    return int(v) if v else default


class _KernelBatchVerifier(BatchVerifier):
    """Shared body of the TPU-batched verifiers: a scalar fallback below
    batch_min (a kernel launch never pays off for a handful of sigs), the
    kernel dispatch, and metrics. Subclasses name the scalar + ops modules."""

    _scalar_module: str
    _ops_module: str
    _kind: str = ""
    _batch_min_default: int = 32

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key.bytes(), msg, sig))

    @classmethod
    def _module(cls, spec_attr: str) -> object:
        """Resolve + cache cls.<spec_attr> per class: the hot addVote drain
        flushes thousands of times per second, and an importlib round trip
        (sys.modules lookup + lock) per flush is pure overhead. Cached
        separately per module so the pure-Python scalar path never imports
        the ops module (whose top level pulls in jax)."""
        cache_attr = spec_attr + "_cache"
        mod = cls.__dict__.get(cache_attr)
        if mod is None:
            import importlib

            mod = importlib.import_module(getattr(cls, spec_attr))
            setattr(cls, cache_attr, mod)
        return mod

    def dispatch(self, force_device: bool = False) -> PendingVerify:
        """Issue host prep + device dispatch without fetching. Returns a
        PendingVerify whose resolve() -> (all_ok, bitmap). Small batches
        verify scalar immediately (no device output to fetch).
        force_device=True pins the device kernel regardless of the host
        crossover (pipelined callers whose chunks overlap other host
        work)."""
        items, self._items = self._items, []
        from tendermint_tpu.ops import chost

        if (not force_device
                and len(items) < batch_min(self._batch_min_default)
                and not chost.available()):
            # Pure-Python scalar fallback only when the C host verifier is
            # missing: with it, the ops dispatch routes ANY size to the host
            # path below the measured crossover (VERDICT r4 item 1a).
            scalar = self._module("_scalar_module")
            out = [scalar.verify(p, m, s) for (p, m, s) in items]
            return PendingVerify([None], lambda _f, _r=(all(out), out): _r)
        # DEVICE-BOUND batches route through the continuous-batching verify
        # service (crypto/verify_service.py): ONE device-owning executor
        # coalesces concurrent dispatches into shared kernel launches, so N
        # simultaneous callers pay one sync floor, not N. Sub-crossover
        # host batches (inline C verify, no floor) stay direct — a thread
        # hop + coalescing window per tiny flush is pure loss there. The
        # service calls the same ops dispatch_batch below (same routing,
        # fault sites, breaker), so the bitmap is byte-identical;
        # TMTPU_VERIFY_SERVICE=0 restores direct dispatch for everything,
        # =1 forces everything onto the service (tests/bench).
        from tendermint_tpu.crypto import verify_service

        if verify_service.enabled() and (
                verify_service.force_all()
                or verify_service.device_bound(len(items), force_device)):
            return verify_service.get().submit(self._kind, items,
                                               force_device=force_device)
        import time as _t

        from tendermint_tpu.utils import metrics as tmmetrics

        ops = self._module("_ops_module")
        started = _t.monotonic()
        if _trace.ENABLED:  # flight recorder: host-prep phase attribution
            tracer = _trace.current()
            with tracer.span("verify.host_prep", n=len(items)):
                dev, finish = ops.dispatch_batch(items,
                                                 force_device=force_device)
        else:
            tracer = None
            dev, finish = ops.dispatch_batch(items, force_device=force_device)

        def resolve(fetched):
            out = [bool(b) for b in finish(fetched[0])]
            if tmmetrics.GLOBAL_NODE_METRICS is not None:
                m = tmmetrics.GLOBAL_NODE_METRICS
                m.batch_verify_seconds.observe(_t.monotonic() - started)
                m.batch_verify_sigs.add(len(items))
            return all(out), out

        p = PendingVerify([dev], resolve)
        if tracer is not None and tracer.enabled:
            p._tracer = tracer
            p._t_disp = _t.monotonic()
            p._t_height = tracer.current_height()
        return p

    def verify(self) -> tuple[bool, list[bool]]:
        return self.dispatch().resolve()

    def __len__(self) -> int:
        return len(self._items)


class Ed25519BatchVerifier(_KernelBatchVerifier):
    """TPU-batched ed25519 (tendermint_tpu.ops.ed25519_batch)."""

    _scalar_module = "tendermint_tpu.crypto.ed25519"
    _ops_module = "tendermint_tpu.ops.ed25519_batch"
    _kind = "ed25519"


class Sr25519BatchVerifier(_KernelBatchVerifier):
    """TPU-batched sr25519 (tendermint_tpu.ops.sr25519_batch): the Edwards
    comb kernel with merlin challenges batched in C. The reference verifies
    sr25519 serially through go-schnorrkel (crypto/sr25519/pubkey.go:10)."""

    _scalar_module = "tendermint_tpu.crypto.sr25519"
    _ops_module = "tendermint_tpu.ops.sr25519_batch"
    _kind = "sr25519"
    # Pure-Python scalar fallback costs ~18 ms/sig; the kernel pays off
    # almost immediately.
    _batch_min_default = 8


class MixedBatchVerifier(BatchVerifier):
    """Routes items to a per-key-type verifier, preserving item order in the
    result bitmap. Lets commits with mixed ed25519/sr25519/secp256k1 validator
    sets still batch the ed25519 majority."""

    def __init__(self) -> None:
        self._order: list[tuple[str, int]] = []
        self._subs: dict[str, BatchVerifier] = {}

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        kt = pub_key.type
        sub = self._subs.get(kt)
        if sub is None:
            sub = create_batch_verifier(kt)
            self._subs[kt] = sub
        self._order.append((kt, len(sub)))
        sub.add(pub_key, msg, sig)

    def dispatch(self, force_device: bool = False) -> PendingVerify:
        """Issue every key type's dispatch without fetching. The returned
        PendingVerify's device-output list is the concatenation of every
        sub-verifier's outputs, so one resolve() (or a cross-decision
        prefetch) fetches a mixed ed25519+sr25519 commit in ONE device_get
        — the tunnel round trip is latency-bound, so each extra fetch costs
        a full floor."""
        spans = []  # (key type, sub PendingVerify, offset into devs, n devs)
        devs: list = []
        for kt, sub in self._subs.items():
            p = sub.dispatch(force_device=force_device)
            spans.append((kt, p, len(devs), len(p._devs)))
            devs.extend(p._devs)
        order = self._order
        self._order = []
        self._subs = {}

        def resolve(fetched):
            results = {}
            for kt, p, off, n in spans:
                if not p.resolved:
                    p._finish(fetched[off:off + n])
                results[kt] = p._result[1]
            out = [results[kt][i] for (kt, i) in order]
            return all(out), out

        # Children make has_device_output() see through to service-backed
        # sub-handles (their shared launch is in flight but they carry no
        # device outputs of their own), so async callers keep overlapping.
        mixed = PendingVerify(devs, resolve,
                              children=[p for (_, p, _, _) in spans])
        if _trace.ENABLED:
            tracer = _trace.current()
            # Own the queue/readback attribution UNLESS a service-backed
            # child is involved: the service executor already records those
            # phases per request, and a second caller-side queue record
            # would double-count the wait. Host-resolved and direct-device
            # mixed batches keep their pre-service span coverage.
            svc_children = any(isinstance(p, ServicePending)
                               for (_, p, _, _) in spans)
            if tracer.enabled and not svc_children:
                mixed._tracer = tracer
                mixed._t_disp = _time.monotonic()
                mixed._t_height = tracer.current_height()
        return mixed

    def verify(self) -> tuple[bool, list[bool]]:
        # Dispatch every key type's kernel first, then fetch ALL results in
        # one device_get: the tunnel readback is latency-bound, so a mixed
        # ed25519+sr25519 commit pays one fetch floor instead of two.
        return self.dispatch().resolve()

    def __len__(self) -> int:
        return len(self._order)


_WARMED = False


def warmup(sizes: tuple[int, ...] = (64,), background: bool = True):
    """AOT-warm the batch kernel at the given bucket sizes.

    XLA compiles one executable per padded bucket shape; the first launch at a
    new bucket pays ~20-40 s of tracing+compilation. Nodes call this at start
    (in a background thread by default) so the first real commit at a warm
    bucket size is a cache hit, not a compile. No-op when batching is disabled
    or already warmed. Returns the warmup thread when background, else None."""
    global _WARMED
    if (_WARMED or os.environ.get("TM_TPU_DISABLE_BATCH") == "1"
            or os.environ.get("TM_TPU_SKIP_WARMUP") == "1"):
        # TM_TPU_SKIP_WARMUP: short-lived processes (tests) exit while a
        # background XLA compile is mid-flight, which aborts the C++ runtime
        # at teardown ("FATAL: exception not rethrown"); they also gain
        # nothing from pre-compiling kernels they may never launch.
        return None
    _WARMED = True

    def _run():
        try:
            from tendermint_tpu.crypto import ed25519
            from tendermint_tpu.ops import ed25519_batch

            # Measure the host/kernel crossover first so the warm buckets
            # below compile the path real batches will actually take.
            ed25519_batch.calibrate_host_crossover()
            priv = ed25519.gen_priv_key(b"\x42" * 32)
            pub = priv.pub_key().bytes()
            sig = ed25519.sign(priv.data, b"warmup")
            for n in sizes:
                # force_device: the point is compiling the kernel buckets,
                # which the host route would otherwise absorb
                ed25519_batch.verify_batch([(pub, b"warmup", sig)] * n,
                                           force_device=True)
            _warm_mesh(pub, sig)
        except Exception:  # noqa: BLE001 - warmup must never kill a node
            return

    def _warm_mesh(pub, sig):
        """Compile the multi-device shard_map chunk executables so the first
        real sharded commit doesn't eat the trace (+compile). One chunk is
        n_devices * JNP_TILE items; the sharded path only ever runs that one
        shape, so one warm call per kernel covers every future batch size."""
        import jax

        from tendermint_tpu.ops import ed25519_batch
        from tendermint_tpu.parallel import batch_shard

        if jax.local_device_count() < 2 or not batch_shard.shard_enabled():
            return
        chunk = jax.local_device_count() * ed25519_batch.JNP_TILE
        n = max(chunk, batch_shard.shard_threshold(jax.local_device_count()))
        ed25519_batch.verify_batch([(pub, b"warmup", sig)] * n,
                                   force_device=True)
        try:
            from tendermint_tpu.crypto import sr25519
            from tendermint_tpu.ops import sr25519_batch

            spriv = sr25519.gen_priv_key(b"\x43" * 32)
            spub = spriv.pub_key().bytes()
            ssig = spriv.sign(b"warmup")
            sr25519_batch.verify_batch([(spub, b"warmup", ssig)] * n)
        except Exception:  # noqa: BLE001 - sr warm is best-effort
            pass

    if background:
        import threading

        t = threading.Thread(target=_run, name="batch-warmup", daemon=True)
        t.start()
        return t
    _run()
    return None


_BATCH_TYPES: dict[str, type] = {}


def register_batch_verifier(key_type: str, cls: type) -> None:
    _BATCH_TYPES[key_type] = cls


def supports_batch(key_type: str) -> bool:
    _ensure()
    return key_type in _BATCH_TYPES


def create_batch_verifier(key_type: str | None = None) -> BatchVerifier:
    """Batch verifier for one key type, or a mixed router when None."""
    _ensure()
    if key_type is None:
        return MixedBatchVerifier()
    cls = _BATCH_TYPES.get(key_type, ScalarBatchVerifier)
    return cls()


def _ensure() -> None:
    if _BATCH_TYPES:
        return
    if os.environ.get("TM_TPU_DISABLE_BATCH") == "1":
        _BATCH_TYPES["_disabled"] = ScalarBatchVerifier
        return
    _BATCH_TYPES["ed25519"] = Ed25519BatchVerifier
    _BATCH_TYPES["sr25519"] = Sr25519BatchVerifier
