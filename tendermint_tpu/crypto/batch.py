"""BatchVerifier: the pluggable batch signature-verification registry.

THE capability the reference lacks entirely (SURVEY.md: v0.34 has no
BatchVerifier interface; every verify path is a serial loop over
crypto.PubKey.VerifySignature, reference crypto/crypto.go:22-28). This module
introduces it: callers accumulate (pubkey, msg, sig) triples and flush them in
one call, which on TPU becomes a single wide Edwards-curve kernel launch
(tendermint_tpu.ops.ed25519_batch).

Semantics contract: `verify()` returns a per-item bitmap whose entries are
byte-identical to what the scalar `pub_key.verify_signature` path returns for
the same item. Callers that need the reference's serial early-exit/error-
attribution behavior (e.g. ValidatorSet.VerifyCommitLight) replay the serial
decision procedure over the bitmap -- verification is batched, the consensus
semantics are not changed.
"""

from __future__ import annotations

import abc
import os

from tendermint_tpu.crypto import keys


class BatchVerifier(abc.ABC):
    @abc.abstractmethod
    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        """Queue one (pubkey, message, signature) item."""

    @abc.abstractmethod
    def verify(self) -> tuple[bool, list[bool]]:
        """Verify everything queued. Returns (all_ok, per-item bitmap) and
        resets the queue."""

    @abc.abstractmethod
    def __len__(self) -> int: ...


class ScalarBatchVerifier(BatchVerifier):
    """Fallback: the reference's serial loop, for key types without a batch
    kernel (and for differential testing)."""

    def __init__(self) -> None:
        self._items: list[tuple[keys.PubKey, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        out = [pk.verify_signature(m, s) for (pk, m, s) in self._items]
        self._items = []
        return all(out), out

    def __len__(self) -> int:
        return len(self._items)


class Ed25519BatchVerifier(BatchVerifier):
    """TPU-batched ed25519 (tendermint_tpu.ops.ed25519_batch)."""

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key.bytes(), msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        from tendermint_tpu.ops import ed25519_batch

        bitmap = ed25519_batch.verify_batch(self._items)
        self._items = []
        out = [bool(b) for b in bitmap]
        return all(out), out

    def __len__(self) -> int:
        return len(self._items)


class MixedBatchVerifier(BatchVerifier):
    """Routes items to a per-key-type verifier, preserving item order in the
    result bitmap. Lets commits with mixed ed25519/sr25519/secp256k1 validator
    sets still batch the ed25519 majority."""

    def __init__(self) -> None:
        self._order: list[tuple[str, int]] = []
        self._subs: dict[str, BatchVerifier] = {}

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        kt = pub_key.type
        sub = self._subs.get(kt)
        if sub is None:
            sub = create_batch_verifier(kt)
            self._subs[kt] = sub
        self._order.append((kt, len(sub)))
        sub.add(pub_key, msg, sig)

    def verify(self) -> tuple[bool, list[bool]]:
        results = {kt: sub.verify()[1] for kt, sub in self._subs.items()}
        out = [results[kt][i] for (kt, i) in self._order]
        self._order = []
        self._subs = {}
        return all(out), out

    def __len__(self) -> int:
        return len(self._order)


_BATCH_TYPES: dict[str, type] = {}


def register_batch_verifier(key_type: str, cls: type) -> None:
    _BATCH_TYPES[key_type] = cls


def supports_batch(key_type: str) -> bool:
    _ensure()
    return key_type in _BATCH_TYPES


def create_batch_verifier(key_type: str | None = None) -> BatchVerifier:
    """Batch verifier for one key type, or a mixed router when None."""
    _ensure()
    if key_type is None:
        return MixedBatchVerifier()
    cls = _BATCH_TYPES.get(key_type, ScalarBatchVerifier)
    return cls()


def _ensure() -> None:
    if _BATCH_TYPES:
        return
    if os.environ.get("TM_TPU_DISABLE_BATCH") == "1":
        _BATCH_TYPES["_disabled"] = ScalarBatchVerifier
        return
    _BATCH_TYPES["ed25519"] = Ed25519BatchVerifier
