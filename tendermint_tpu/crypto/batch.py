"""BatchVerifier: the pluggable batch signature-verification registry.

THE capability the reference lacks entirely (SURVEY.md: v0.34 has no
BatchVerifier interface; every verify path is a serial loop over
crypto.PubKey.VerifySignature, reference crypto/crypto.go:22-28). This module
introduces it: callers accumulate (pubkey, msg, sig) triples and flush them in
one call, which on TPU becomes a single wide Edwards-curve kernel launch
(tendermint_tpu.ops.ed25519_batch).

Semantics contract: `verify()` returns a per-item bitmap whose entries are
byte-identical to what the scalar `pub_key.verify_signature` path returns for
the same item. Callers that need the reference's serial early-exit/error-
attribution behavior (e.g. ValidatorSet.VerifyCommitLight) replay the serial
decision procedure over the bitmap -- verification is batched, the consensus
semantics are not changed.
"""

from __future__ import annotations

import abc
import os

from tendermint_tpu.crypto import keys


class BatchVerifier(abc.ABC):
    @abc.abstractmethod
    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        """Queue one (pubkey, message, signature) item."""

    @abc.abstractmethod
    def verify(self) -> tuple[bool, list[bool]]:
        """Verify everything queued. Returns (all_ok, per-item bitmap) and
        resets the queue."""

    @abc.abstractmethod
    def __len__(self) -> int: ...


class ScalarBatchVerifier(BatchVerifier):
    """Fallback: the reference's serial loop, for key types without a batch
    kernel (and for differential testing)."""

    def __init__(self) -> None:
        self._items: list[tuple[keys.PubKey, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        out = [pk.verify_signature(m, s) for (pk, m, s) in self._items]
        self._items = []
        return all(out), out

    def __len__(self) -> int:
        return len(self._items)


def batch_min(default: int = 32) -> int:
    """Batch-size threshold below which the kernel is never launched.

    A 1-vote commit (single-validator chains, gossiped singles) must not pay
    kernel dispatch -- and on a cold process must not pay XLA compilation.
    The crossover depends on the SCALAR path's speed, so each verifier
    passes its own default: ed25519's scalar path is ~1-3 ms/sig (crossover
    in the tens of sigs), sr25519's is pure Python at ~18 ms/sig (crossover
    ~8). TM_TPU_BATCH_MIN overrides both."""
    v = os.environ.get("TM_TPU_BATCH_MIN")
    return int(v) if v else default


class _KernelBatchVerifier(BatchVerifier):
    """Shared body of the TPU-batched verifiers: a scalar fallback below
    batch_min (a kernel launch never pays off for a handful of sigs), the
    kernel dispatch, and metrics. Subclasses name the scalar + ops modules."""

    _scalar_module: str
    _ops_module: str
    _batch_min_default: int = 32

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        self._items.append((pub_key.bytes(), msg, sig))

    def dispatch(self, force_device: bool = False):
        """Issue host prep + device dispatch without fetching. Returns
        (device_out_or_None, resolve) where resolve(fetched) -> (all_ok,
        bitmap); fetch device_out with jax.device_get. Small batches verify
        scalar immediately (device_out None). force_device=True pins the
        device kernel regardless of the host crossover (pipelined callers
        whose chunks overlap other host work)."""
        import importlib

        items, self._items = self._items, []
        from tendermint_tpu.ops import chost

        if (not force_device
                and len(items) < batch_min(self._batch_min_default)
                and not chost.available()):
            # Pure-Python scalar fallback only when the C host verifier is
            # missing: with it, the ops dispatch routes ANY size to the host
            # path below the measured crossover (VERDICT r4 item 1a).
            scalar = importlib.import_module(self._scalar_module)
            out = [scalar.verify(p, m, s) for (p, m, s) in items]
            return None, lambda _: (all(out), out)
        import time as _t

        from tendermint_tpu.utils import metrics as tmmetrics

        ops = importlib.import_module(self._ops_module)
        started = _t.monotonic()
        dev, finish = ops.dispatch_batch(items, force_device=force_device)

        def resolve(fetched):
            out = [bool(b) for b in finish(fetched)]
            if tmmetrics.GLOBAL_NODE_METRICS is not None:
                m = tmmetrics.GLOBAL_NODE_METRICS
                m.batch_verify_seconds.observe(_t.monotonic() - started)
                m.batch_verify_sigs.add(len(items))
            return all(out), out

        return dev, resolve

    def verify(self) -> tuple[bool, list[bool]]:
        import jax

        dev, resolve = self.dispatch()
        return resolve(jax.device_get(dev) if dev is not None else None)

    def __len__(self) -> int:
        return len(self._items)


class Ed25519BatchVerifier(_KernelBatchVerifier):
    """TPU-batched ed25519 (tendermint_tpu.ops.ed25519_batch)."""

    _scalar_module = "tendermint_tpu.crypto.ed25519"
    _ops_module = "tendermint_tpu.ops.ed25519_batch"


class Sr25519BatchVerifier(_KernelBatchVerifier):
    """TPU-batched sr25519 (tendermint_tpu.ops.sr25519_batch): the Edwards
    comb kernel with merlin challenges batched in C. The reference verifies
    sr25519 serially through go-schnorrkel (crypto/sr25519/pubkey.go:10)."""

    _scalar_module = "tendermint_tpu.crypto.sr25519"
    _ops_module = "tendermint_tpu.ops.sr25519_batch"
    # Pure-Python scalar fallback costs ~18 ms/sig; the kernel pays off
    # almost immediately.
    _batch_min_default = 8


class MixedBatchVerifier(BatchVerifier):
    """Routes items to a per-key-type verifier, preserving item order in the
    result bitmap. Lets commits with mixed ed25519/sr25519/secp256k1 validator
    sets still batch the ed25519 majority."""

    def __init__(self) -> None:
        self._order: list[tuple[str, int]] = []
        self._subs: dict[str, BatchVerifier] = {}

    def add(self, pub_key: keys.PubKey, msg: bytes, sig: bytes) -> None:
        kt = pub_key.type
        sub = self._subs.get(kt)
        if sub is None:
            sub = create_batch_verifier(kt)
            self._subs[kt] = sub
        self._order.append((kt, len(sub)))
        sub.add(pub_key, msg, sig)

    def dispatch(self, force_device: bool = False):
        """Issue every key type's dispatch without fetching. Returns
        (devs, resolve) where devs is a list of device arrays (None entries
        for host-resolved sub-batches) and resolve(jax.device_get(devs)) ->
        (all_ok, bitmap). Lets callers batch readbacks of SEVERAL flushes
        (range sync chunks) into one device_get — the tunnel round trip is
        latency-bound, so each extra fetch costs a full floor."""
        pairs = []
        for kt, sub in self._subs.items():
            if hasattr(sub, "dispatch"):
                pairs.append((kt,) + sub.dispatch(force_device=force_device))
            else:
                res = sub.verify()
                pairs.append((kt, None, lambda _fetched, _res=res: _res))
        order = self._order
        self._order = []
        self._subs = {}
        devs = [d for (_, d, _) in pairs]

        def resolve(fetched):
            results = {}
            for (kt, _d, res), f in zip(pairs, fetched):
                results[kt] = res(f)[1]
            out = [results[kt][i] for (kt, i) in order]
            return all(out), out

        return devs, resolve

    def verify(self) -> tuple[bool, list[bool]]:
        # Dispatch every key type's kernel first, then fetch ALL results in
        # one device_get: the tunnel readback is latency-bound, so a mixed
        # ed25519+sr25519 commit pays one fetch floor instead of two.
        import jax

        devs, resolve = self.dispatch()
        return resolve(jax.device_get(devs))

    def __len__(self) -> int:
        return len(self._order)


_WARMED = False


def warmup(sizes: tuple[int, ...] = (64,), background: bool = True):
    """AOT-warm the batch kernel at the given bucket sizes.

    XLA compiles one executable per padded bucket shape; the first launch at a
    new bucket pays ~20-40 s of tracing+compilation. Nodes call this at start
    (in a background thread by default) so the first real commit at a warm
    bucket size is a cache hit, not a compile. No-op when batching is disabled
    or already warmed. Returns the warmup thread when background, else None."""
    global _WARMED
    if (_WARMED or os.environ.get("TM_TPU_DISABLE_BATCH") == "1"
            or os.environ.get("TM_TPU_SKIP_WARMUP") == "1"):
        # TM_TPU_SKIP_WARMUP: short-lived processes (tests) exit while a
        # background XLA compile is mid-flight, which aborts the C++ runtime
        # at teardown ("FATAL: exception not rethrown"); they also gain
        # nothing from pre-compiling kernels they may never launch.
        return None
    _WARMED = True

    def _run():
        try:
            from tendermint_tpu.crypto import ed25519
            from tendermint_tpu.ops import ed25519_batch

            # Measure the host/kernel crossover first so the warm buckets
            # below compile the path real batches will actually take.
            ed25519_batch.calibrate_host_crossover()
            priv = ed25519.gen_priv_key(b"\x42" * 32)
            pub = priv.pub_key().bytes()
            sig = ed25519.sign(priv.data, b"warmup")
            for n in sizes:
                # force_device: the point is compiling the kernel buckets,
                # which the host route would otherwise absorb
                ed25519_batch.verify_batch([(pub, b"warmup", sig)] * n,
                                           force_device=True)
        except Exception:  # noqa: BLE001 - warmup must never kill a node
            return

    if background:
        import threading

        t = threading.Thread(target=_run, name="batch-warmup", daemon=True)
        t.start()
        return t
    _run()
    return None


_BATCH_TYPES: dict[str, type] = {}


def register_batch_verifier(key_type: str, cls: type) -> None:
    _BATCH_TYPES[key_type] = cls


def supports_batch(key_type: str) -> bool:
    _ensure()
    return key_type in _BATCH_TYPES


def create_batch_verifier(key_type: str | None = None) -> BatchVerifier:
    """Batch verifier for one key type, or a mixed router when None."""
    _ensure()
    if key_type is None:
        return MixedBatchVerifier()
    cls = _BATCH_TYPES.get(key_type, ScalarBatchVerifier)
    return cls()


def _ensure() -> None:
    if _BATCH_TYPES:
        return
    if os.environ.get("TM_TPU_DISABLE_BATCH") == "1":
        _BATCH_TYPES["_disabled"] = ScalarBatchVerifier
        return
    _BATCH_TYPES["ed25519"] = Ed25519BatchVerifier
    _BATCH_TYPES["sr25519"] = Sr25519BatchVerifier
