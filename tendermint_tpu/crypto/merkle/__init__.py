"""RFC-6962 Merkle tree over SHA-256 (reference: crypto/merkle/tree.go:9,
crypto/merkle/hash.go, crypto/merkle/proof.go).

leaf hash  = SHA-256(0x00 || leaf)
inner hash = SHA-256(0x01 || left || right)
split point = largest power of two strictly less than n
empty tree  = SHA-256("")

A batched TPU path (tendermint_tpu.ops.merkle_kernel) computes whole levels of
the tree as one SHA-256 batch; this module is the scalar reference and the
proof machinery.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def empty_hash() -> bytes:
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def split_point(n: int) -> int:
    """Largest power of 2 strictly less than n (reference:
    crypto/merkle/tree.go getSplitPoint)."""
    if n < 1:
        raise ValueError("n must be >= 1")
    k = 1 << ((n - 1).bit_length() - 1)
    return k if k < n else k >> 1


# Below this many items the recursive hashlib path wins (no FFI/array setup).
_BATCH_THRESHOLD = 64


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    if n >= _BATCH_THRESHOLD:
        return _hash_from_byte_slices_batched(items)
    k = split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


def _hash_from_byte_slices_batched(items: list[bytes]) -> bytes:
    """Level-order batched evaluation of the RFC-6962 tree: the t=1 case
    of hash_trees_fixed (one shared copy of the pairing loop)."""
    return hash_trees_fixed([items])[0]


def hash_trees_fixed(trees: list[list[bytes]]) -> list[bytes]:
    """Roots of T same-arity RFC-6962 trees in O(log n) C-batched calls.

    The reference split rule (largest power of two < n,
    crypto/merkle/tree.go getSplitPoint) equals repeatedly pairing adjacent
    nodes left-to-right and promoting a trailing odd node unchanged; every
    tree has the same level structure, so all T trees advance one level per
    sha256 batch. Used to hash header CHAINS (each header = a fixed
    14-field tree, types/block.go:440-476) where per-tree batching never
    kicks in; the single-tree batched path is the t=1 case."""
    import numpy as np

    from tendermint_tpu.ops import chash

    t = len(trees)
    if t == 0:
        return []
    n = len(trees[0])
    if any(len(tr) != n for tr in trees):
        raise ValueError("hash_trees_fixed requires same-arity trees")
    if n == 0:
        return [empty_hash()] * t
    flat = [LEAF_PREFIX + it for tr in trees for it in tr]
    level = chash.sha256_many(flat).reshape(t, n, 32)
    prefix = INNER_PREFIX[0]
    while level.shape[1] > 1:
        n = level.shape[1]
        pairs = n // 2
        rows = np.empty((t, pairs, 65), dtype=np.uint8)
        rows[:, :, 0] = prefix
        rows[:, :, 1:33] = level[:, 0:2 * pairs:2]
        rows[:, :, 33:65] = level[:, 1:2 * pairs:2]
        hashed = chash.sha256_fixed(
            np.ascontiguousarray(rows.reshape(t * pairs, 65))
        ).reshape(t, pairs, 32)
        if n % 2:
            level = np.concatenate([hashed, level[:, n - 1:]], axis=1)
        else:
            level = hashed
    return [level[i, 0].tobytes() for i in range(t)]


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        if self.total < 0:
            raise ValueError("proof total must be positive")
        if self.index < 0:
            raise ValueError("proof index cannot be negative")
        if leaf_hash(leaf) != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root_hash:
            raise ValueError("invalid root hash")

    def compute_root_hash(self) -> bytes | None:
        return _compute_hash_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)


def _compute_hash_from_aunts(index: int, total: int, leaf: bytes, aunts: list[bytes]) -> bytes | None:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf
    if not aunts:
        return None
    k = split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + one inclusion proof per item."""
    trails, root = _trails_from_byte_slices(items)
    root_hash = root.hash
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            Proof(total=len(items), index=i, leaf_hash=trail.hash, aunts=trail.flatten_aunts())
        )
    return root_hash, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None
        self.right = None

    def flatten_aunts(self) -> list[bytes]:
        aunts = []
        node = self
        while node.parent is not None:
            parent = node.parent
            sibling = parent.right if parent.left is node else parent.left
            aunts.append(sibling.hash)
            node = parent
        return aunts


def _trails_from_byte_slices(items: list[bytes]) -> tuple[list[_Node], _Node]:
    n = len(items)
    if n == 0:
        return [], _Node(empty_hash())
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    root.left, root.right = left_root, right_root
    left_root.parent = right_root.parent = root
    return lefts + rights, root
