"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go, which
wraps btcd's btcec).

Semantics preserved:
 - PrivKey = 32 bytes; PubKey = 33-byte compressed SEC1 point.
 - Address = RIPEMD160(SHA256(compressed_pubkey)) (secp256k1.go:40) --
   bitcoin-style, NOT the 20-byte tmhash truncation ed25519 uses.
 - Sign: deterministic RFC 6979 nonce over SHA-256(msg), 64-byte R||S with
   S canonicalized to the lower half-order (btcec signRFC6979 + malleability
   rule).
 - VerifySignature rejects S > halforder (secp256k1_nocgo.go:43) and
   otherwise runs standard ECDSA over SHA-256(msg).

Host-only scalar math: secp256k1 validators are a rare minority key type in
practice; the BatchVerifier registry routes them to the scalar fallback while
the ed25519 majority batches on TPU (crypto/batch.py MixedBatchVerifier).
"""

from __future__ import annotations

import hashlib
import hmac
import os

from tendermint_tpu.crypto import keys

KEY_TYPE = "secp256k1"
PUBKEY_SIZE = 33
PRIVKEY_SIZE = 32
SIGNATURE_SIZE = 64

# curve parameters (SEC2)
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
HALF_N = N // 2
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv_mod(a: int, m: int) -> int:
    return pow(a, -1, m)


# --- Jacobian point arithmetic ---------------------------------------------


def _jac_double(p):
    x, y, z = p
    if y == 0:
        return (0, 0, 0)
    s = 4 * x * y * y % P
    m = 3 * x * x % P  # a = 0 for secp256k1
    x2 = (m * m - 2 * s) % P
    y2 = (m * (s - x2) - 8 * pow(y, 4, P)) % P
    z2 = 2 * y * z % P
    return (x2, y2, z2)


def _jac_add(p, q):
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return (0, 0, 0)
        return _jac_double(p)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = h * h % P
    h3 = h * h2 % P
    x3 = (r * r - h3 - 2 * u1 * h2) % P
    y3 = (r * (u1 * h2 - x3) - s1 * h3) % P
    z3 = h * z1 * z2 % P
    return (x3, y3, z3)


def _jac_mul(k: int, p) -> tuple[int, int, int]:
    acc = (0, 0, 0)
    add = p
    while k:
        if k & 1:
            acc = _jac_add(acc, add)
        add = _jac_double(add)
        k >>= 1
    return acc


def _to_affine(p) -> tuple[int, int] | None:
    x, y, z = p
    if z == 0:
        return None
    zi = _inv_mod(z, P)
    zi2 = zi * zi % P
    return (x * zi2 % P, y * zi2 * zi % P)


_G = (GX, GY, 1)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(data: bytes) -> tuple[int, int] | None:
    if len(data) != PUBKEY_SIZE or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


# --- RFC 6979 deterministic nonce ------------------------------------------


def _rfc6979_k(priv: int, h1: bytes) -> int:
    """Deterministic k per RFC 6979 sec 3.2 with HMAC-SHA256 (what btcec
    uses: signRFC6979)."""
    x = priv.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# --- sign / verify ----------------------------------------------------------


def sign(priv_bytes: bytes, msg: bytes) -> bytes:
    d = int.from_bytes(priv_bytes, "big")
    if not 1 <= d < N:
        raise ValueError("invalid secp256k1 private key")
    h1 = hashlib.sha256(msg).digest()
    e = int.from_bytes(h1, "big") % N
    while True:
        k = _rfc6979_k(d, h1)
        pt = _to_affine(_jac_mul(k, _G))
        if pt is None:
            continue
        r = pt[0] % N
        if r == 0:
            continue
        s = _inv_mod(k, N) * (e + r * d) % N
        if s == 0:
            continue
        if s > HALF_N:  # low-S canonical form
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub_bytes: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != SIGNATURE_SIZE:
        return False
    pt = _decompress(pub_bytes)
    if pt is None:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    if s > HALF_N:  # reject malleable high-S (reference secp256k1_nocgo.go:43)
        return False
    e = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
    w = _inv_mod(s, N)
    u1 = e * w % N
    u2 = r * w % N
    res = _jac_add(_jac_mul(u1, _G), _jac_mul(u2, (pt[0], pt[1], 1)))
    aff = _to_affine(res)
    if aff is None:
        return False
    return aff[0] % N == r


# --- key classes ------------------------------------------------------------


class PubKey(keys.PubKey):
    def __init__(self, data: bytes):
        self.data = bytes(data)

    @property
    def type(self) -> str:
        return KEY_TYPE

    def address(self) -> bytes:
        """RIPEMD160(SHA256(pubkey)) (reference: secp256k1.go:40)."""
        sha = hashlib.sha256(self.data).digest()
        rip = hashlib.new("ripemd160")
        rip.update(sha)
        return rip.digest()

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify(self.data, msg, sig)

    def equals(self, other) -> bool:
        return isinstance(other, PubKey) and self.data == other.data

    def __repr__(self) -> str:
        return f"PubKeySecp256k1{{{self.data.hex().upper()}}}"


class PrivKey(keys.PrivKey):
    def __init__(self, data: bytes):
        if len(data) != PRIVKEY_SIZE:
            raise ValueError("secp256k1 private key must be 32 bytes")
        self.data = bytes(data)

    @property
    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        return sign(self.data, msg)

    def pub_key(self) -> PubKey:
        d = int.from_bytes(self.data, "big")
        pt = _to_affine(_jac_mul(d, _G))
        return PubKey(_compress(*pt))

    def equals(self, other) -> bool:
        return isinstance(other, PrivKey) and hmac.compare_digest(self.data, other.data)


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    """reference: secp256k1.go GenPrivKey (rejection-samples mod N)."""
    data = seed
    while True:
        if data is None:
            data = os.urandom(32)
        else:
            data = hashlib.sha256(data).digest()
        d = int.from_bytes(data, "big")
        if 1 <= d < N:
            return PrivKey(data)
        data = None
