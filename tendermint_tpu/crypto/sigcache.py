"""Bounded verified-signature cache for the gossip vote-drain paths.

Gossip delivers the same vote from several peers: without a cache every copy
re-pays a kernel or scalar verification before the duplicate check in
VoteSet.add_vote drops it (the reference pays the same tax -- one scalar
verify per gossiped copy, types/vote_set.go:205). A verification result is a
pure function of the (pubkey, message, signature) triple, so a bounded LRU
of known-good triples lets repeat deliveries skip straight to the serial
accept-replay.

Design constraints:

 * Keys are SHA-256 digests of pubkey||msg||sig (length-framed, so no
   concatenation of a different triple can collide), 32 bytes per entry --
   the vote bytes themselves are never retained.
 * ONLY positive results are cached, and only from a RESOLVED bitmap: a
   dispatch that degrades through the circuit breaker still resolves to a
   host-verified bitmap (safe to cache), while a resolve that raises caches
   nothing -- an injected device failure (TMTPU_FAULTS) can therefore never
   poison the cache, and a tampered signature (bitmap False) is never
   remembered as valid.
 * Bounded: least-recently-used eviction at the cap.

Knobs: TM_TPU_SIGCACHE=0 disables; TM_TPU_SIGCACHE_CAP sets the entry cap
(default 65536; ~2 MiB of digests at the default). Hits/misses export as
sigcache_hits_total / sigcache_misses_total (utils/metrics.py).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
from collections import OrderedDict

DEFAULT_CAP = 65536


def cache_key(pub: bytes, msg: bytes, sig: bytes) -> bytes:
    """SHA-256 of the length-framed triple."""
    h = hashlib.sha256(struct.pack("<II", len(pub), len(msg)))
    h.update(pub)
    h.update(msg)
    h.update(sig)
    return h.digest()


class SigCache:
    """Thread-safe LRU set of verified-signature digests."""

    def __init__(self, cap: int = DEFAULT_CAP):
        self.cap = cap
        self._od: OrderedDict[bytes, bool] = OrderedDict()
        self._mtx = threading.Lock()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._od)

    def lookup(self, key: bytes) -> bool:
        """True when `key` is a known-verified triple (LRU-refreshed).
        Counts locally only -- DrainCache batches the node-metrics mirror
        once per drain, so the hot vote path never pays a per-signature
        metrics-mutex acquisition."""
        with self._mtx:
            present = key in self._od
            if present:
                self._od.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        return present

    def hit(self, key: bytes) -> bool:
        """lookup() plus an immediate node-metrics mirror (standalone
        callers outside a drain)."""
        present = self.lookup(key)
        _count(present)
        return present

    def add(self, key: bytes) -> None:
        """Record a POSITIVELY verified triple; evicts LRU beyond the cap."""
        with self._mtx:
            self._od[key] = True
            self._od.move_to_end(key)
            while len(self._od) > self.cap:
                self._od.popitem(last=False)

    def clear(self) -> None:
        with self._mtx:
            self._od.clear()
            self.hits = 0
            self.misses = 0


def _count(hit: bool) -> None:
    from tendermint_tpu.utils import metrics as tmmetrics

    m = tmmetrics.GLOBAL_NODE_METRICS
    if m is not None:
        (m.sigcache_hits if hit else m.sigcache_misses).add()


class DrainCache:
    """Per-flush consult-and-populate accumulator for the vote-drain call
    sites (ConsensusState._handle_vote_batch, VoteSet.add_votes). Owns THE
    cache-safety invariant in one place: only POSITIVE lanes of a RESOLVED
    bitmap ever enter the cache (``commit`` runs after resolve; a resolve
    that raises never reaches it).

    ``check(i, ...)`` either records index ``i`` as cache-verified (True)
    or records the triple's key aligned with the caller's verify queue
    (False -> caller queues item ``i``); ``commit(queued, bitmap)`` caches
    the positives, flushes the batched hit/miss metrics deltas (ONE counter
    add per drain, not one per vote), and returns the merged
    {index: verified} map."""

    __slots__ = ("_cache", "cached_ok", "_ckeys", "_hits", "_misses")

    def __init__(self):
        self._cache = get()
        self.cached_ok: dict[int, bool] = {}
        self._ckeys: list[bytes | None] = []
        self._hits = 0
        self._misses = 0

    def check(self, i: int, pub: bytes, msg: bytes, sig: bytes) -> bool:
        if self._cache is not None:
            ck = cache_key(pub, msg, sig)
            if self._cache.lookup(ck):
                self._hits += 1
                self.cached_ok[i] = True
                return True
            self._misses += 1
        else:
            ck = None
        self._ckeys.append(ck)
        return False

    def commit(self, queued: list, bitmap) -> dict:
        self._flush_metrics()
        if self._cache is not None:
            for ok, ck in zip(bitmap, self._ckeys):
                if ok and ck is not None:
                    self._cache.add(ck)
        out = dict(self.cached_ok)
        out.update(zip(queued, bitmap))
        return out

    def _flush_metrics(self) -> None:
        if not (self._hits or self._misses):
            return
        from tendermint_tpu.utils import metrics as tmmetrics

        m = tmmetrics.GLOBAL_NODE_METRICS
        if m is not None:
            if self._hits:
                m.sigcache_hits.add(self._hits)
            if self._misses:
                m.sigcache_misses.add(self._misses)
        self._hits = self._misses = 0


_CACHE: SigCache | None = None
_CACHE_LOCK = threading.Lock()


def get() -> SigCache | None:
    """The process-wide cache, or None when disabled (TM_TPU_SIGCACHE=0).
    The cap (TM_TPU_SIGCACHE_CAP) is read at first use."""
    if os.environ.get("TM_TPU_SIGCACHE") == "0":
        return None
    global _CACHE
    if _CACHE is None:
        with _CACHE_LOCK:
            if _CACHE is None:
                cap = int(os.environ.get("TM_TPU_SIGCACHE_CAP", DEFAULT_CAP))
                _CACHE = SigCache(cap)
    return _CACHE


def reset() -> None:
    """Drop the process-wide cache (tests; also re-reads the cap knob)."""
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None
