"""tmhash = SHA-256, with 20-byte truncated addresses
(reference: crypto/tmhash/hash.go)."""

from __future__ import annotations

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum(b: bytes) -> bytes:  # noqa: A001 - mirrors reference naming
    return hashlib.sha256(b).digest()


def sum_truncated(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()[:TRUNCATED_SIZE]
