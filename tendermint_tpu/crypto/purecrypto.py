"""Pure-Python X25519 + ChaCha20-Poly1305 fallback for SecretConnection.

The container may lack the ``cryptography`` package; this module provides
drop-in shims with the same API surface SecretConnection uses
(X25519PrivateKey.generate/public_key/exchange, ChaCha20Poly1305
encrypt/decrypt).  Implementations follow RFC 7748 (X25519) and RFC 8439
(ChaCha20-Poly1305) exactly — tests/test_purecrypto.py pins the RFC test
vectors — so peers using this fallback interoperate with peers using the
C-backed package.  Python-speed only; the p2p frame path tolerates it.
"""

from __future__ import annotations

import hmac
import os
import struct

# --- X25519 (RFC 7748) ------------------------------------------------------

_P = 2**255 - 19
_A24 = 121665


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _decode_u(u: bytes) -> int:
    b = bytearray(u)
    b[31] &= 127
    return int.from_bytes(bytes(b), "little")


def x25519(k: bytes, u: bytes) -> bytes:
    """Scalar multiplication on Curve25519 (Montgomery ladder)."""
    if len(k) != 32 or len(u) != 32:
        raise ValueError("x25519 inputs must be 32 bytes")
    ki = _decode_scalar(k)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (ki >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        return cls(data)

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(x25519(self._raw, _X25519_BASE))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        shared = x25519(self._raw, peer_public_key.public_bytes_raw())
        if shared == b"\x00" * 32:
            raise ValueError("x25519 shared secret is all zeros")
        return shared


# --- ChaCha20 (RFC 8439 §2.3) -----------------------------------------------

_MASK32 = 0xFFFFFFFF


def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
    st = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        key_words[0], key_words[1], key_words[2], key_words[3],
        key_words[4], key_words[5], key_words[6], key_words[7],
        counter, nonce_words[0], nonce_words[1], nonce_words[2],
    ]
    x = list(st)
    for _ in range(10):
        for a, b, c, d in (
            (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
            (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
        ):
            xa, xb, xc, xd = x[a], x[b], x[c], x[d]
            xa = (xa + xb) & _MASK32
            xd ^= xa
            xd = ((xd << 16) | (xd >> 16)) & _MASK32
            xc = (xc + xd) & _MASK32
            xb ^= xc
            xb = ((xb << 12) | (xb >> 20)) & _MASK32
            xa = (xa + xb) & _MASK32
            xd ^= xa
            xd = ((xd << 8) | (xd >> 24)) & _MASK32
            xc = (xc + xd) & _MASK32
            xb ^= xc
            xb = ((xb << 7) | (xb >> 25)) & _MASK32
            x[a], x[b], x[c], x[d] = xa, xb, xc, xd
    return struct.pack("<16I", *((x[i] + st[i]) & _MASK32 for i in range(16)))


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    key_words = struct.unpack("<8I", key)
    nonce_words = struct.unpack("<3I", nonce)
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = _chacha20_block(key_words, counter + i // 64, nonce_words)
        chunk = data[i : i + 64]
        ks = int.from_bytes(block[: len(chunk)], "little")
        pt = int.from_bytes(chunk, "little")
        out[i : i + len(chunk)] = (ks ^ pt).to_bytes(len(chunk), "little")
    return bytes(out)


# --- Poly1305 (RFC 8439 §2.5) -----------------------------------------------

_P1305 = 2**130 - 5
_CLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def poly1305_mac(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & _CLAMP
    s = int.from_bytes(key[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block, "little") + (1 << (8 * len(block)))
        acc = (acc + n) * r % _P1305
    return ((acc + s) & (2**128 - 1)).to_bytes(16, "little")


# --- AEAD construction (RFC 8439 §2.8) --------------------------------------


class InvalidTag(Exception):
    pass


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return data + (b"\x00" * (16 - rem) if rem else b"")


class ChaCha20Poly1305:
    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = chacha20_xor(self._key, 0, nonce, b"\x00" * 32)
        mac_data = (
            _pad16(aad)
            + _pad16(ct)
            + struct.pack("<Q", len(aad))
            + struct.pack("<Q", len(ct))
        )
        return poly1305_mac(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = associated_data or b""
        ct = chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext too short")
        aad = associated_data or b""
        ct, tag = data[:-16], data[-16:]
        if not hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("poly1305 tag mismatch")
        return chacha20_xor(self._key, 1, nonce, ct)
