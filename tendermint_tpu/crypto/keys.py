"""Key interfaces + registry (reference: crypto/crypto.go:22-42,
crypto/encoding/codec.go).

`PubKey`/`PrivKey` are the pluggable key abstractions; concrete types register
themselves by type name ("ed25519", "sr25519", "secp256k1") so wire decoding
and genesis parsing can round-trip any supported key.
"""

from __future__ import annotations

import abc

ADDRESS_SIZE = 20


class PubKey(abc.ABC):
    @property
    @abc.abstractmethod
    def type(self) -> str: ...

    @abc.abstractmethod
    def address(self) -> bytes: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abc.abstractmethod
    def equals(self, other) -> bool: ...

    def __eq__(self, other):
        return isinstance(other, PubKey) and self.equals(other)

    def __hash__(self):
        return hash((self.type, self.bytes()))


class PrivKey(abc.ABC):
    @property
    @abc.abstractmethod
    def type(self) -> str: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def equals(self, other) -> bool: ...


_PUBKEY_TYPES: dict[str, type] = {}
_PRIVKEY_TYPES: dict[str, type] = {}


def register(name: str, pub_cls: type, priv_cls: type) -> None:
    _PUBKEY_TYPES[name] = pub_cls
    _PRIVKEY_TYPES[name] = priv_cls


def pubkey_from_type_bytes(name: str, data: bytes) -> PubKey:
    _ensure_registered()
    try:
        return _PUBKEY_TYPES[name](data)
    except KeyError:
        raise ValueError(f"unknown pubkey type {name!r}") from None


def privkey_from_type_bytes(name: str, data: bytes) -> PrivKey:
    _ensure_registered()
    try:
        return _PRIVKEY_TYPES[name](data)
    except KeyError:
        raise ValueError(f"unknown privkey type {name!r}") from None


def _ensure_registered() -> None:
    if not _PUBKEY_TYPES:
        from tendermint_tpu.crypto import ed25519  # noqa: F401

        register(ed25519.KEY_TYPE, ed25519.PubKey, ed25519.PrivKey)
        try:
            from tendermint_tpu.crypto import sr25519  # noqa: F401

            register(sr25519.KEY_TYPE, sr25519.PubKey, sr25519.PrivKey)
        except ImportError:
            pass
        try:
            from tendermint_tpu.crypto import secp256k1  # noqa: F401

            register(secp256k1.KEY_TYPE, secp256k1.PubKey, secp256k1.PrivKey)
        except ImportError:
            pass
