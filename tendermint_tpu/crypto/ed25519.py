"""Ed25519: scalar (CPU) reference implementation + key types.

This is the framework's bit-exact reference path. Accept/reject semantics
mirror Go's crypto/ed25519 (which the reference uses via
golang.org/x/crypto/ed25519 — reference: crypto/ed25519/ed25519.go:9,148):

  1. signature must be 64 bytes and S strictly canonical (S < L);
  2. the public key A must decode per RFC 8032 (y < p, x recoverable,
     and not (x == 0 with sign bit set));
  3. h = SHA-512(R || A || msg) reduced mod L;
  4. accept iff encode([S]B - [h]A) == sig[:32] byte-for-byte
     (R itself is never decoded — non-canonical R bytes fail the compare).

The TPU batched kernel (tendermint_tpu.ops.ed25519_kernel) is property-tested
against this module for identical accept/reject decisions.

Key formats follow the reference: 32-byte public keys, 64-byte private keys
(seed || public), 20-byte addresses = SHA-256(pub)[:20]
(reference: crypto/ed25519/ed25519.go, crypto/tmhash/hash.go).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

from tendermint_tpu.crypto import keys as _keys

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64
SEED_SIZE = 32
SIGNATURE_SIZE = 64

KEY_TYPE = "ed25519"


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, xy=T/Z.
_IDENT = (0, 1, 1, 0)


def _add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = B - A, Dd - C, Dd + C, B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _double(p):
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = H - (X1 + Y1) * (X1 + Y1) % P
    G = A - B
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _scalarmult(s: int, p):
    q = _IDENT
    while s:
        if s & 1:
            q = _add(q, p)
        p = _double(p)
        s >>= 1
    return q


def _compress(p) -> bytes:
    X, Y, Z, _ = p
    zi = _inv(Z)
    x, y = X * zi % P, Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(s: bytes):
    """RFC 8032 §5.1.3 point decoding. Returns extended point or None."""
    if len(s) != 32:
        return None
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # candidate root x = (u/v)^((p+3)/8) computed as u v^3 (u v^7)^((p-5)/8)
    x = u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vx2 = v * x * x % P
    if vx2 == u % P:
        pass
    elif vx2 == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, x * y % P)


# Base point
_By = 4 * _inv(5) % P
_Bx = 0
# recover Bx from By with even sign
_B = _decompress(_By.to_bytes(32, "little"))
assert _B is not None
BASE = _B


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def pubkey_from_seed(seed: bytes) -> bytes:
    if len(seed) != SEED_SIZE:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    return _compress(_scalarmult(a, BASE))


def sign(priv: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signature; priv is the 64-byte (seed||pub) key."""
    if len(priv) != PRIVKEY_SIZE:
        raise ValueError("ed25519 private key must be 64 bytes")
    seed, pub = priv[:32], priv[32:]
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = _compress(_scalarmult(r, BASE))
    k = int.from_bytes(hashlib.sha512(R + pub + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Scalar verification, bit-exact with Go crypto/ed25519 semantics."""
    if len(pub) != PUBKEY_SIZE or len(sig) != SIGNATURE_SIZE:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    A = _decompress(pub)
    if A is None:
        return False
    h = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
    # R' = [s]B + [h](-A); negate A by negating X and T.
    negA = (P - A[0], A[1], A[2], (P - A[3]) % P)
    Rp = _add(_scalarmult(s, BASE), _scalarmult(h, negA))
    return _compress(Rp) == sig[:32]


def generate_seed() -> bytes:
    return os.urandom(SEED_SIZE)


# --- key object layer (reference: crypto/crypto.go:22-42) -------------------


@dataclass(frozen=True)
class PubKey(_keys.PubKey):
    data: bytes

    def __post_init__(self):
        if len(self.data) != PUBKEY_SIZE:
            raise ValueError("invalid ed25519 public key size")

    @property
    def type(self) -> str:
        return KEY_TYPE

    def address(self) -> bytes:
        from tendermint_tpu.crypto import tmhash

        return tmhash.sum_truncated(self.data)

    def bytes(self) -> bytes:
        return self.data

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # Production scalar path routes through the C host verifier
        # (~100 us/sig vs ~2 ms pure Python); `verify()` above stays the
        # pure-Python reference that kernels differential-test against.
        from tendermint_tpu.ops import chost

        if chost.available():
            return chost.ed25519_verify_one(self.data, msg, sig)
        return verify(self.data, msg, sig)

    def equals(self, other) -> bool:
        return isinstance(other, PubKey) and other.data == self.data


@dataclass(frozen=True)
class PrivKey(_keys.PrivKey):
    data: bytes

    def __post_init__(self):
        if len(self.data) != PRIVKEY_SIZE:
            raise ValueError("invalid ed25519 private key size")

    @property
    def type(self) -> str:
        return KEY_TYPE

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        return sign(self.data, msg)

    def pub_key(self) -> PubKey:
        return PubKey(self.data[32:])

    def equals(self, other) -> bool:
        return isinstance(other, PrivKey) and other.data == self.data


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    seed = seed if seed is not None else generate_seed()
    return PrivKey(seed + pubkey_from_seed(seed))
