"""UPnP IGD port mapping (reference: p2p/upnp/upnp.go, probe.go).

Discovers an Internet Gateway Device via SSDP multicast, fetches its root
description to find the WANIPConnection control URL, then drives the SOAP
actions the reference uses: GetExternalIPAddress, AddPortMapping,
DeletePortMapping.

Pure stdlib (socket + urllib + minimal XML scraping); the discovery probe
is what `tendermint probe-upnp` runs (reference: probe.go:15 Probe).
"""

from __future__ import annotations

import re
import socket
import urllib.request
from dataclasses import dataclass

SSDP_ADDR = "239.255.255.250"
SSDP_PORT = 1900
SEARCH_TARGET = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
_SERVICE_TYPES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


@dataclass
class IGD:
    """A discovered gateway (reference: upnp.go upnpNAT)."""

    location: str
    control_url: str
    service_type: str


def discover(timeout_s: float = 3.0, ssdp_addr: str = SSDP_ADDR,
             ssdp_port: int = SSDP_PORT) -> IGD:
    """SSDP M-SEARCH for an IGD (reference: upnp.go:77 Discover)."""
    msg = (
        "M-SEARCH * HTTP/1.1\r\n"
        f"HOST: {ssdp_addr}:{ssdp_port}\r\n"
        'MAN: "ssdp:discover"\r\n'
        f"ST: {SEARCH_TARGET}\r\n"
        "MX: 2\r\n\r\n"
    ).encode()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout_s)
    try:
        s.sendto(msg, (ssdp_addr, ssdp_port))
        while True:
            try:
                data, _ = s.recvfrom(4096)
            except socket.timeout:
                raise UPnPError("no UPnP gateway responded") from None
            m = re.search(rb"(?im)^location:\s*(\S+)", data)
            if m:
                return _probe_location(m.group(1).decode())
    finally:
        s.close()


def _probe_location(location: str) -> IGD:
    """Fetch the root description and locate the WAN connection control URL
    (reference: upnp.go getServiceURL)."""
    with urllib.request.urlopen(location, timeout=5) as r:
        desc = r.read().decode(errors="replace")
    for st in _SERVICE_TYPES:
        # serviceType block followed by its controlURL
        pat = re.compile(
            r"<serviceType>\s*" + re.escape(st)
            + r"\s*</serviceType>.*?<controlURL>\s*([^<]+?)\s*</controlURL>",
            re.S)
        m = pat.search(desc)
        if m:
            control = m.group(1)
            if not control.startswith("http"):
                base = location.split("/", 3)
                control = f"{base[0]}//{base[2]}{control if control.startswith('/') else '/' + control}"
            return IGD(location=location, control_url=control, service_type=st)
    raise UPnPError("gateway exposes no WAN*Connection service")


def _soap(igd: IGD, action: str, args_xml: str) -> str:
    body = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{igd.service_type}">{args_xml}'
        f"</u:{action}></s:Body></s:Envelope>"
    ).encode()
    req = urllib.request.Request(
        igd.control_url, data=body,
        headers={
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{igd.service_type}#{action}"',
        })
    with urllib.request.urlopen(req, timeout=5) as r:
        return r.read().decode(errors="replace")


def get_external_ip(igd: IGD) -> str:
    """reference: upnp.go GetExternalIPAddress."""
    resp = _soap(igd, "GetExternalIPAddress", "")
    m = re.search(r"<NewExternalIPAddress>\s*([^<]+?)\s*</NewExternalIPAddress>",
                  resp)
    if not m:
        raise UPnPError("no external IP in gateway response")
    return m.group(1)


def _local_ip_for(igd: IGD) -> str:
    host = igd.control_url.split("/")[2].rsplit(":", 1)[0]
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((host, 1))
        return s.getsockname()[0]
    finally:
        s.close()


def add_port_mapping(igd: IGD, external_port: int, internal_port: int,
                     protocol: str = "TCP", description: str = "tendermint-tpu",
                     lease_s: int = 0, internal_ip: str = "") -> None:
    """reference: upnp.go AddPortMapping."""
    ip = internal_ip or _local_ip_for(igd)
    _soap(igd, "AddPortMapping", (
        "<NewRemoteHost></NewRemoteHost>"
        f"<NewExternalPort>{external_port}</NewExternalPort>"
        f"<NewProtocol>{protocol}</NewProtocol>"
        f"<NewInternalPort>{internal_port}</NewInternalPort>"
        f"<NewInternalClient>{ip}</NewInternalClient>"
        "<NewEnabled>1</NewEnabled>"
        f"<NewPortMappingDescription>{description}</NewPortMappingDescription>"
        f"<NewLeaseDuration>{lease_s}</NewLeaseDuration>"
    ))


def delete_port_mapping(igd: IGD, external_port: int,
                        protocol: str = "TCP") -> None:
    """reference: upnp.go DeletePortMapping."""
    _soap(igd, "DeletePortMapping", (
        "<NewRemoteHost></NewRemoteHost>"
        f"<NewExternalPort>{external_port}</NewExternalPort>"
        f"<NewProtocol>{protocol}</NewProtocol>"
    ))


def probe(timeout_s: float = 3.0, **discover_kwargs) -> dict:
    """Capability probe (reference: probe.go:15): discover, fetch the
    external IP, round-trip a test mapping."""
    igd = discover(timeout_s, **discover_kwargs)
    out = {"location": igd.location, "control_url": igd.control_url,
           "service_type": igd.service_type}
    out["external_ip"] = get_external_ip(igd)
    add_port_mapping(igd, 26656, 26656, description="tendermint-tpu probe")
    delete_port_mapping(igd, 26656)
    out["port_mapping"] = "ok"
    return out
