"""NodeInfo: identity/version handshake payload (reference: p2p/node_info.go,
proto/tendermint/p2p/types.proto DefaultNodeInfo)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.encoding import proto

P2P_PROTOCOL = 8     # reference: version/version.go:18
BLOCK_PROTOCOL = 11  # reference: version/version.go:21
MAX_NUM_CHANNELS = 64


@dataclass
class NodeInfo:
    p2p_version: int = P2P_PROTOCOL
    block_version: int = BLOCK_PROTOCOL
    app_version: int = 0
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""
    version: str = "0.34.24-tpu"
    channels: bytes = b""
    moniker: str = ""
    tx_index: str = "on"
    rpc_address: str = ""

    def validate_basic(self) -> None:
        if len(self.node_id) != 40:
            raise ValueError("invalid node ID")
        if len(self.channels) > MAX_NUM_CHANNELS:
            raise ValueError("too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise ValueError("duplicate channel id")

    def compatible_with(self, other: "NodeInfo") -> None:
        """reference: p2p/node_info.go CompatibleWith."""
        if self.block_version != other.block_version:
            raise ValueError(
                f"peer is on a different Block version. Got {other.block_version}, "
                f"expected {self.block_version}"
            )
        if self.network != other.network:
            raise ValueError(
                f"peer is on a different network. Got {other.network!r}, "
                f"expected {self.network!r}"
            )
        if not self.channels:
            return
        if not any(ch in self.channels for ch in other.channels):
            raise ValueError("peer has no common channels")

    def marshal(self) -> bytes:
        pv = (
            proto.Writer()
            .uvarint(1, self.p2p_version)
            .uvarint(2, self.block_version)
            .uvarint(3, self.app_version)
            .out()
        )
        other = proto.Writer().string(1, self.tx_index).string(2, self.rpc_address).out()
        return (
            proto.Writer()
            .message(1, pv, always=True)
            .string(2, self.node_id)
            .string(3, self.listen_addr)
            .string(4, self.network)
            .string(5, self.version)
            .bytes(6, self.channels)
            .string(7, self.moniker)
            .message(8, other, always=True)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "NodeInfo":
        f = proto.fields(buf)
        pv = proto.fields(f.get(1, [b""])[-1])
        other = proto.fields(f.get(8, [b""])[-1])
        return NodeInfo(
            p2p_version=pv.get(1, [0])[-1],
            block_version=pv.get(2, [0])[-1],
            app_version=pv.get(3, [0])[-1],
            node_id=f.get(2, [b""])[-1].decode(),
            listen_addr=f.get(3, [b""])[-1].decode(),
            network=f.get(4, [b""])[-1].decode(),
            version=f.get(5, [b""])[-1].decode(),
            channels=f.get(6, [b""])[-1],
            moniker=f.get(7, [b""])[-1].decode(),
            tx_index=other.get(1, [b"on"])[-1].decode() if 1 in other else "on",
            rpc_address=other.get(2, [b""])[-1].decode() if 2 in other else "",
        )
