"""Bucketed peer address book (reference: p2p/pex/addrbook.go).

Addresses live in NEW buckets (heard about, never connected) until
mark_good() promotes them to OLD buckets (had a successful connection).
Bucket placement is keyed by a per-book random key hashed with the
address group and (for new addresses) the source's group, which caps how
much of the book a single /16 of sybils can occupy — the same eclipse
defence as the reference (addrbook.go:118 design notes).

Persistence is JSON, loaded at start and saved on a dirty flag.
"""

from __future__ import annotations

import hashlib
import ipaddress
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
NEW_BUCKETS_PER_ADDRESS = 4
OLD_BUCKETS_PER_ADDRESS = 2  # reference allows 1; kept 1 effectively below
MAX_NEW_BUCKET_SIZE = 64
MAX_OLD_BUCKET_SIZE = 64
GET_SELECTION_PERCENT = 23  # reference: addrbook.go getSelection
MAX_GET_SELECTION = 250
BIASED_NEW_PCT_DEFAULT = 30


@dataclass
class NetAddress:
    """id@host:port (reference: p2p/netaddress.go)."""

    node_id: str
    host: str
    port: int

    @staticmethod
    def parse(s: str) -> "NetAddress":
        if "@" not in s:
            raise ValueError(f"address {s!r} missing node id")
        nid, hp = s.split("@", 1)
        if "://" in hp:
            hp = hp.split("://", 1)[1]
        host, port = hp.rsplit(":", 1)
        return NetAddress(nid.lower(), host, int(port))

    def __str__(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    def dial_string(self) -> str:
        return f"{self.node_id}@{self.host}:{self.port}"

    def is_routable(self) -> bool:
        """reference: netaddress.go Routable; loopback/private fail strict
        mode."""
        try:
            ip = ipaddress.ip_address(self.host)
        except ValueError:
            return True  # hostname: assume routable
        return not (ip.is_loopback or ip.is_private or ip.is_multicast
                    or ip.is_unspecified)

    def group(self) -> str:
        """Eclipse-resistance group: /16 for IPv4 (reference:
        addrbook.go groupKey)."""
        try:
            ip = ipaddress.ip_address(self.host)
        except ValueError:
            return self.host
        if ip.version == 4:
            parts = self.host.split(".")
            return ".".join(parts[:2])
        return str(ipaddress.ip_network(f"{self.host}/32", strict=False))


@dataclass
class _KnownAddress:
    """reference: p2p/pex/known_address.go."""

    addr: NetAddress
    src: NetAddress
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: str = "new"
    buckets: list[int] = field(default_factory=list)

    def is_old(self) -> bool:
        return self.bucket_type == "old"

    def to_json(self) -> dict:
        return {
            "addr": str(self.addr), "src": str(self.src),
            "attempts": self.attempts, "last_attempt": self.last_attempt,
            "last_success": self.last_success, "bucket_type": self.bucket_type,
            "buckets": self.buckets,
        }

    @staticmethod
    def from_json(d: dict) -> "_KnownAddress":
        return _KnownAddress(
            addr=NetAddress.parse(d["addr"]), src=NetAddress.parse(d["src"]),
            attempts=d.get("attempts", 0),
            last_attempt=d.get("last_attempt", 0.0),
            last_success=d.get("last_success", 0.0),
            bucket_type=d.get("bucket_type", "new"),
            buckets=list(d.get("buckets", [])),
        )


class AddrBook:
    """reference: p2p/pex/addrbook.go:120 newAddrBook."""

    def __init__(self, file_path: str = "", strict: bool = True):
        self.file_path = file_path
        self.strict = strict
        self._mtx = threading.RLock()
        self._addrs: dict[str, _KnownAddress] = {}  # node_id -> ka
        self._new_buckets: list[set[str]] = [set() for _ in range(NEW_BUCKET_COUNT)]
        self._old_buckets: list[set[str]] = [set() for _ in range(OLD_BUCKET_COUNT)]
        self._our_ids: set[str] = set()
        self._key = os.urandom(24).hex()
        self._rand = random.Random()
        self._dirty = False
        if file_path and os.path.exists(file_path):
            self._load()

    # --- identity ----------------------------------------------------------

    def add_our_address(self, addr: NetAddress) -> None:
        with self._mtx:
            self._our_ids.add(addr.node_id)

    def our_address(self, addr: NetAddress) -> bool:
        with self._mtx:
            return addr.node_id in self._our_ids

    # --- adding ------------------------------------------------------------

    def add_address(self, addr: NetAddress, src: NetAddress) -> bool:
        """reference: addrbook.go:196 AddAddress. Returns True if added."""
        with self._mtx:
            return self._add_address(addr, src)

    def _add_address(self, addr: NetAddress, src: NetAddress) -> bool:
        if addr.node_id in self._our_ids:
            return False
        if self.strict and not addr.is_routable():
            return False
        ka = self._addrs.get(addr.node_id)
        if ka is not None:
            if ka.is_old():
                return False  # never demote old entries via gossip
            # Already known: small chance to add another new bucket ref
            # (reference: addrbook.go:560).
            if len(ka.buckets) >= NEW_BUCKETS_PER_ADDRESS:
                return False
            factor = 1 << (2 * len(ka.buckets))
            if self._rand.randrange(factor) != 0:
                return False
        else:
            ka = _KnownAddress(addr=addr, src=src)
            self._addrs[addr.node_id] = ka
        bucket = self._calc_new_bucket(addr, src)
        self._add_to_new_bucket(ka, bucket)
        self._dirty = True
        return True

    def _add_to_new_bucket(self, ka: _KnownAddress, bucket: int) -> None:
        if bucket in ka.buckets:
            return
        b = self._new_buckets[bucket]
        if len(b) >= MAX_NEW_BUCKET_SIZE:
            self._expire_new_bucket(bucket)
        b.add(ka.addr.node_id)
        ka.buckets.append(bucket)

    def _expire_new_bucket(self, bucket: int) -> None:
        """Evict the worst entry (most attempts, oldest success) (reference:
        addrbook.go:666 expireNew -> pickOldest)."""
        b = self._new_buckets[bucket]
        if not b:
            return
        worst = max(
            b, key=lambda nid: (self._addrs[nid].attempts,
                                -self._addrs[nid].last_success))
        self._remove_from_bucket(self._addrs[worst], bucket, "new")

    def _remove_from_bucket(self, ka: _KnownAddress, bucket: int, btype: str) -> None:
        (self._new_buckets if btype == "new" else self._old_buckets)[bucket].discard(
            ka.addr.node_id)
        if bucket in ka.buckets:
            ka.buckets.remove(bucket)
        if not ka.buckets:
            self._addrs.pop(ka.addr.node_id, None)

    # --- connection feedback ------------------------------------------------

    def mark_attempt(self, addr: NetAddress) -> None:
        with self._mtx:
            ka = self._addrs.get(addr.node_id)
            if ka is not None:
                ka.attempts += 1
                ka.last_attempt = time.time()
                self._dirty = True

    def mark_good(self, node_id: str) -> None:
        """Successful connection: promote to an old bucket (reference:
        addrbook.go:250 MarkGood -> moveToOld)."""
        with self._mtx:
            ka = self._addrs.get(node_id)
            if ka is None:
                return
            ka.attempts = 0
            ka.last_success = time.time()
            self._dirty = True
            if ka.is_old():
                return
            for b in list(ka.buckets):
                self._new_buckets[b].discard(node_id)
            ka.buckets.clear()
            ka.bucket_type = "old"
            bucket = self._calc_old_bucket(ka.addr)
            ob = self._old_buckets[bucket]
            if len(ob) >= MAX_OLD_BUCKET_SIZE:
                # evict oldest-success back to new (reference moveToOld
                # displacement)
                loser_id = min(ob, key=lambda nid: self._addrs[nid].last_success)
                loser = self._addrs[loser_id]
                ob.discard(loser_id)
                loser.bucket_type = "new"
                loser.buckets.clear()
                self._add_to_new_bucket(loser, self._calc_new_bucket(loser.addr, loser.src))
            ob.add(node_id)
            ka.buckets = [bucket]

    def mark_bad(self, node_id: str) -> None:
        with self._mtx:
            ka = self._addrs.get(node_id)
            if ka is None:
                return
            for b in list(ka.buckets):
                self._remove_from_bucket(ka, b, ka.bucket_type)
            self._dirty = True

    def remove_address(self, addr: NetAddress) -> None:
        self.mark_bad(addr.node_id)

    # --- selection ----------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def is_empty(self) -> bool:
        return self.size() == 0

    def has_address(self, addr: NetAddress) -> bool:
        with self._mtx:
            return addr.node_id in self._addrs

    def pick_address(self, new_bias_pct: int = BIASED_NEW_PCT_DEFAULT) -> NetAddress | None:
        """Random address biased toward new entries (reference:
        addrbook.go:280 PickAddress)."""
        with self._mtx:
            if not self._addrs:
                return None
            new = [ka for ka in self._addrs.values() if not ka.is_old()]
            old = [ka for ka in self._addrs.values() if ka.is_old()]
            pct = max(0, min(100, new_bias_pct))
            pool = new if (self._rand.randrange(100) < pct or not old) else old
            if not pool:
                pool = new or old
            return self._rand.choice(pool).addr if pool else None

    def get_selection(self) -> list[NetAddress]:
        """Random ~23% (max 250) for PEX responses (reference:
        addrbook.go:327 GetSelection)."""
        with self._mtx:
            all_addrs = [ka.addr for ka in self._addrs.values()]
        n = max(min(len(all_addrs), MAX_GET_SELECTION),
                len(all_addrs) * GET_SELECTION_PERCENT // 100)
        self._rand.shuffle(all_addrs)
        return all_addrs[:n]

    # --- bucket hashing (reference: addrbook.go:840-900) --------------------

    def _hash(self, *parts: str) -> int:
        h = hashlib.sha256(("|".join((self._key,) + parts)).encode()).digest()
        return int.from_bytes(h[:8], "big")

    def _calc_new_bucket(self, addr: NetAddress, src: NetAddress) -> int:
        return self._hash("new", addr.group(), src.group()) % NEW_BUCKET_COUNT

    def _calc_old_bucket(self, addr: NetAddress) -> int:
        return self._hash("old", addr.group()) % OLD_BUCKET_COUNT

    # --- persistence (reference: p2p/pex/file.go) ---------------------------

    def save(self) -> None:
        with self._mtx:
            if not self.file_path:
                return
            doc = {"key": self._key,
                   "addrs": [ka.to_json() for ka in self._addrs.values()]}
            tmp = self.file_path + ".tmp"
            os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.file_path)
            self._dirty = False

    def _load(self) -> None:
        with open(self.file_path) as f:
            doc = json.load(f)
        self._key = doc.get("key", self._key)
        for d in doc.get("addrs", []):
            try:
                ka = _KnownAddress.from_json(d)
            except (KeyError, ValueError):
                continue
            self._addrs[ka.addr.node_id] = ka
            for b in ka.buckets:
                if ka.is_old() and b < OLD_BUCKET_COUNT:
                    self._old_buckets[b].add(ka.addr.node_id)
                elif not ka.is_old() and b < NEW_BUCKET_COUNT:
                    self._new_buckets[b].add(ka.addr.node_id)
