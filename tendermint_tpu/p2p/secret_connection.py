"""SecretConnection: authenticated encryption for the peer wire
(reference: p2p/conn/secret_connection.go:63,92,139-143).

STS-shaped construction, v0.33-style key schedule — NOT wire-interoperable
with reference v0.34 nodes (which derive the auth challenge from a Merlin
transcript, secret_connection.go:92-143); framework peers interoperate with
each other:
 1. exchange ephemeral X25519 pubkeys (32 bytes, length-delimited);
 2. DH -> shared secret; HKDF-SHA256 expand to 96 bytes: send/recv keys
    (ordering by lexicographic comparison of the ephemeral pubkeys) plus a
    32-byte challenge (okm[64:96], in place of the reference's Merlin
    transcript challenge);
 3. all further traffic in ChaCha20-Poly1305 sealed frames: 4-byte LE length
    + payload, padded to 1024 bytes; 12-byte nonce with a LE u64 counter in
    bytes [4:12) per direction (same layout as secret_connection.go:455-463);
 4. exchange (node ed25519 pubkey, sig over challenge) inside the encrypted
    channel and verify.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
import threading

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ModuleNotFoundError:  # image without `cryptography`: RFC-exact fallback
    from tendermint_tpu.crypto.purecrypto import (
        ChaCha20Poly1305,
        X25519PrivateKey,
        X25519PublicKey,
    )

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.encoding import proto

DATA_MAX_SIZE = 1024
FRAME_SIZE = 4 + DATA_MAX_SIZE
SEALED_FRAME_SIZE = FRAME_SIZE + 16  # AEAD tag


class SecretConnectionError(Exception):
    pass


def _hkdf_sha256(secret: bytes, info: bytes, length: int) -> bytes:
    """RFC 5869 HKDF with empty salt (reference uses the same)."""
    prk = hmac.new(b"\x00" * 32, secret, hashlib.sha256).digest()
    out = b""
    block = b""
    i = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([i]), hashlib.sha256).digest()
        out += block
        i += 1
    return out[:length]


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise SecretConnectionError("connection closed during read")
        buf += chunk
    return buf


class SecretConnection:
    """Wraps a connected socket. Thread-safe for one reader + one writer."""

    def __init__(self, sock: socket.socket, priv_key: ed25519.PrivKey):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        self._recv_buf = b""
        self._send_nonce = 0
        self._recv_nonce = 0

        # 1. ephemeral key exchange
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        sock.sendall(proto.delimited(proto.Writer().bytes(1, eph_pub).out()))
        hdr = _read_exact(sock, 1)
        # delimited BytesValue: varint len (<=127 here) + msg
        (ln,) = hdr
        msg = _read_exact(sock, ln)
        fields = proto.fields(msg)
        remote_eph = fields.get(1, [b""])[-1]
        if len(remote_eph) != 32:
            raise SecretConnectionError("bad ephemeral key")

        # 2. DH + HKDF key schedule
        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted([eph_pub, remote_eph])
        we_are_lo = eph_pub == lo
        okm = _hkdf_sha256(shared, b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN", 96)
        if we_are_lo:
            recv_key, send_key = okm[0:32], okm[32:64]
        else:
            send_key, recv_key = okm[0:32], okm[32:64]
        challenge = okm[64:96]
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)

        # 3. authenticate: exchange (pubkey, sig(challenge)) encrypted
        sig = priv_key.sign(challenge)
        auth = (
            proto.Writer()
            .message(1, proto.Writer().bytes(1, priv_key.pub_key().bytes()).out(), always=True)
            .bytes(2, sig)
            .out()
        )
        self.write(auth)
        remote_auth = self.read_msg()
        f = proto.fields(remote_auth)
        pk_fields = proto.fields(f.get(1, [b""])[-1])
        remote_pub_bytes = pk_fields.get(1, [b""])[-1]
        remote_sig = f.get(2, [b""])[-1]
        remote_pub = ed25519.PubKey(remote_pub_bytes)
        if not remote_pub.verify_signature(challenge, remote_sig):
            raise SecretConnectionError("challenge verification failed")
        self.remote_pub_key = remote_pub

    # --- framed encrypted IO ----------------------------------------------

    def write(self, data: bytes) -> None:
        """Writes data as one message (split into sealed frames)."""
        with self._send_lock:
            pos = 0
            first = True
            while pos < len(data) or first:
                first = False
                chunk = data[pos : pos + DATA_MAX_SIZE]
                pos += len(chunk)
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (FRAME_SIZE - len(frame))
                nonce = b"\x00" * 4 + struct.pack("<Q", self._send_nonce)
                self._send_nonce += 1
                sealed = self._send_aead.encrypt(nonce, frame, None)
                # _send_lock exists to serialize exactly this write (nonce
                # order must match wire order); it guards nothing else
                self._sock.sendall(sealed)  # tmlint: disable=lock-held-call

    def _read_frame(self) -> bytes:
        sealed = _read_exact(self._sock, SEALED_FRAME_SIZE)
        nonce = b"\x00" * 4 + struct.pack("<Q", self._recv_nonce)
        self._recv_nonce += 1
        try:
            frame = self._recv_aead.decrypt(nonce, sealed, None)
        except Exception as e:  # noqa: BLE001
            raise SecretConnectionError(f"frame decryption failed: {e}") from e
        (ln,) = struct.unpack_from("<I", frame)
        if ln > DATA_MAX_SIZE:
            raise SecretConnectionError("frame length too big")
        return frame[4 : 4 + ln]

    def read(self, max_bytes: int = DATA_MAX_SIZE) -> bytes:
        """Stream-style read of up to max_bytes."""
        with self._recv_lock:
            if not self._recv_buf:
                self._recv_buf = self._read_frame()
            out = self._recv_buf[:max_bytes]
            self._recv_buf = self._recv_buf[max_bytes:]
            return out

    def read_msg(self) -> bytes:
        """Reads one frame's payload (used during handshake)."""
        with self._recv_lock:
            return self._read_frame()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
