"""Node identity key (reference: p2p/key.go).

Node ID = hex(address of ed25519 node pubkey) (20 bytes -> 40 hex chars).
"""

from __future__ import annotations

import base64
import json
import os

from tendermint_tpu.crypto import ed25519

ID_BYTE_LENGTH = 20


class NodeKey:
    def __init__(self, priv_key: ed25519.PrivKey):
        self.priv_key = priv_key

    def id(self) -> str:
        return self.priv_key.pub_key().address().hex()

    def pub_key(self) -> ed25519.PubKey:
        return self.priv_key.pub_key()

    def save_as(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = {
            "priv_key": {
                "type": "tendermint/PrivKeyEd25519",
                "value": base64.b64encode(self.priv_key.bytes()).decode(),
            }
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)

    @staticmethod
    def load(path: str) -> "NodeKey":
        with open(path) as f:
            doc = json.load(f)
        return NodeKey(ed25519.PrivKey(base64.b64decode(doc["priv_key"]["value"])))

    @staticmethod
    def load_or_gen(path: str) -> "NodeKey":
        if os.path.exists(path):
            return NodeKey.load(path)
        nk = NodeKey(ed25519.gen_priv_key())
        nk.save_as(path)
        return nk


def validate_id(node_id: str) -> None:
    if len(node_id) != 2 * ID_BYTE_LENGTH:
        raise ValueError(f"invalid node ID length {len(node_id)}")
    bytes.fromhex(node_id)
