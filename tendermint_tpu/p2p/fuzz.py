"""Fuzzed connection wrapper for network-fault testing (reference:
p2p/fuzz.go FuzzedConnection).

Wraps any read/write/close connection object and injects faults on writes
and reads according to the configured mode:
  drop  -- silently discard the payload with probability prob_drop_rw
  sleep -- delay the op by a random interval up to max_delay_s
  dead  -- after `die_after_s`, every op raises (a vanished peer)

Used by adversarial tests to prove reactors survive lossy/laggy peers; the
reference exposes the same knobs via FuzzConnConfig.
"""

from __future__ import annotations

import random
import time


class FuzzedConnection:
    """reference: p2p/fuzz.go:23 FuzzedConnection."""

    def __init__(self, conn, *, prob_drop_rw: float = 0.0,
                 prob_sleep: float = 0.0, max_delay_s: float = 0.1,
                 die_after_s: float = 0.0, seed: int | None = None):
        self._conn = conn
        self.prob_drop_rw = prob_drop_rw
        self.prob_sleep = prob_sleep
        self.max_delay_s = max_delay_s
        self._die_at = time.monotonic() + die_after_s if die_after_s else None
        self._rng = random.Random(seed)

    def _fuzz(self) -> bool:
        """Returns True when the op should be dropped."""
        if self._die_at is not None and time.monotonic() >= self._die_at:
            raise ConnectionError("fuzzed connection died")
        if self.prob_sleep and self._rng.random() < self.prob_sleep:
            time.sleep(self._rng.random() * self.max_delay_s)
        return bool(self.prob_drop_rw and self._rng.random() < self.prob_drop_rw)

    def write(self, data: bytes) -> int:
        if self._fuzz():
            return len(data)  # silently dropped (reference Write fuzz)
        return self._conn.write(data)

    def read(self, n: int) -> bytes:
        if self._fuzz():
            # A dropped read on a framed/AEAD stream looks like EOF -- the
            # peer abruptly dying, which is exactly the fault worth testing.
            return b""
        return self._conn.read(n)

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
