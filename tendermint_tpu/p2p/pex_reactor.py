"""PEX reactor: peer exchange over channel 0x00 (reference:
p2p/pex/pex_reactor.go; proto/tendermint/p2p/pex.proto).

Messages: PexRequest=1{}, PexAddrs=2{addrs=1 repeated
PexAddress{id=1,ip=2,port=3}}.

Discovery loop: learn addresses from peers, persist them in the AddrBook,
and keep dialing book addresses until the outbound slots are full. Seed
mode answers one address request and hangs up, serving purely as a
bootstrap directory (reference: pex_reactor.go:396 seed crawler).
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.encoding import proto
from tendermint_tpu.p2p.addrbook import AddrBook, NetAddress
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor

PEX_CHANNEL = 0x00

# reference: pex_reactor.go:33-45
ENSURE_PEERS_INTERVAL_S = 1.0  # reference 30s; fast mesh healing for tests
REQUEST_INTERVAL_S = 2.0  # min interval between requests we ACCEPT per peer
SEED_DISCONNECT_DELAY_S = 2.0


def msg_pex_request() -> bytes:
    return proto.Writer().message(1, b"", always=True).out()


def msg_pex_addrs(addrs: list[NetAddress]) -> bytes:
    w = proto.Writer()
    inner = proto.Writer()
    for a in addrs:
        inner.message(1, proto.Writer().string(1, a.node_id).string(2, a.host)
                      .uvarint(3, a.port).out(), always=True)
    w.message(2, inner.out(), always=True)
    return w.out()


def _parse_addrs(buf: bytes) -> list[NetAddress]:
    out = []
    for ab in proto.fields(buf).get(1, []):
        f = proto.fields(ab)
        try:
            out.append(NetAddress(
                node_id=f.get(1, [b""])[-1].decode().lower(),
                host=f.get(2, [b""])[-1].decode(),
                port=f.get(3, [0])[-1]))
        except (UnicodeDecodeError, ValueError):
            continue
    return out


class PexReactor(Reactor):
    """reference: p2p/pex/pex_reactor.go:55."""

    def __init__(self, book: AddrBook, seed_mode: bool = False,
                 seeds: list[str] | None = None, logger=None):
        super().__init__("PEX")
        self.book = book
        self.seed_mode = seed_mode
        self.seeds = [s for s in (seeds or []) if s]
        self.logger = logger
        self._last_request_from: dict[str, float] = {}  # inbound rate limit
        self._requested: set[str] = set()  # peers we asked for addrs
        self._mtx = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None
        self._dialing: set[str] = set()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(PEX_CHANNEL, priority=1,
                                  recv_message_capacity=64 * 1024)]

    # --- lifecycle ----------------------------------------------------------

    def on_start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._ensure_peers_routine,
                                        name="pex-ensure", daemon=True)
        self._thread.start()

    def on_stop(self) -> None:
        self._running = False
        self.book.save()

    # --- peer lifecycle -----------------------------------------------------

    def _peer_net_address(self, peer: Peer) -> NetAddress | None:
        la = peer.node_info.listen_addr
        if not la:
            return None
        try:
            hp = la.split("://", 1)[1] if "://" in la else la
            host, port = hp.rsplit(":", 1)
            if host in ("0.0.0.0", "::"):
                # substitute the socket's remote host
                host = peer.socket_addr.rsplit(":", 1)[0].split("@")[-1]
            return NetAddress(peer.id, host, int(port))
        except (ValueError, IndexError):
            return None

    def add_peer(self, peer: Peer) -> None:
        """reference: pex_reactor.go:130 AddPeer."""
        na = self._peer_net_address(peer)
        if peer.outbound:
            # We dialed them: the address works.
            if na is not None:
                self.book.add_address(na, na)
            self.book.mark_good(peer.id)
            if not self.seed_mode:
                self._request_addrs(peer)
        else:
            # Inbound: record the self-reported listen addr.
            if na is not None:
                self.book.add_address(na, na)
            if self.seed_mode:
                # Serve a selection then hang up shortly (reference seed flow).
                peer.try_send(PEX_CHANNEL, msg_pex_addrs(self.book.get_selection()))

                def later_drop():
                    time.sleep(SEED_DISCONNECT_DELAY_S)
                    if self.switch is not None and peer.id in self.switch.peers:
                        self.switch.stop_peer_for_error(peer, "seed served addrs")

                threading.Thread(target=later_drop, daemon=True).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._mtx:
            self._requested.discard(peer.id)
            self._last_request_from.pop(peer.id, None)

    # --- receive ------------------------------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 in f:  # PexRequest
            now = time.monotonic()
            with self._mtx:
                last = self._last_request_from.get(peer.id, 0.0)
                if now - last < REQUEST_INTERVAL_S and not self.seed_mode:
                    return  # rate-limited (reference: receiveRequest flood guard)
                self._last_request_from[peer.id] = now
            peer.try_send(PEX_CHANNEL, msg_pex_addrs(self.book.get_selection()))
        elif 2 in f:  # PexAddrs
            with self._mtx:
                if peer.id not in self._requested and not peer.outbound:
                    # unsolicited addrs from an inbound peer: ignore
                    # (reference: ReceiveAddrs ErrUnsolicitedList)
                    return
                self._requested.discard(peer.id)
            src = self._peer_net_address(peer) or NetAddress(peer.id, "0.0.0.0", 0)
            for na in _parse_addrs(f[2][-1]):
                self.book.add_address(na, src)

    def _request_addrs(self, peer: Peer) -> None:
        with self._mtx:
            self._requested.add(peer.id)
        peer.try_send(PEX_CHANNEL, msg_pex_request())

    # --- discovery loop (reference: pex_reactor.go:270 ensurePeersRoutine) --

    def _ensure_peers_routine(self) -> None:
        # Bootstrap from configured seeds when the book is empty.
        while self._running:
            try:
                self._ensure_peers()
            except Exception:  # noqa: BLE001 - discovery must never die
                pass
            time.sleep(ENSURE_PEERS_INTERVAL_S)

    def _ensure_peers(self) -> None:
        sw = self.switch
        if sw is None:
            return
        out, inbound = sw.num_peers()
        need = sw.max_outbound - out
        if need <= 0:
            return
        if self.book.is_empty() and self.seeds:
            for s in self.seeds:
                try:
                    na = NetAddress.parse(s)
                except ValueError:
                    continue
                if na.node_id not in sw.peers:
                    self._dial(na)
            return
        tried = 0
        while need > 0 and tried < 10:
            tried += 1
            na = self.book.pick_address()
            if na is None:
                break
            if (na.node_id in sw.peers or self.book.our_address(na)
                    or na.node_id in self._dialing):
                continue
            if self._dial(na):
                need -= 1
        # Still starving: ask a random connected peer for more addresses.
        if need > 0:
            with sw._peers_mtx:
                peers = list(sw.peers.values())
            if peers:
                import random

                self._request_addrs(random.choice(peers))

    def _dial(self, na: NetAddress) -> bool:
        self._dialing.add(na.node_id)
        try:
            self.book.mark_attempt(na)
            peer = self.switch.dial_peer(na.dial_string())
            if peer is not None:
                self.book.mark_good(na.node_id)
                return True
            return False
        finally:
            self._dialing.discard(na.node_id)
