"""Peer behaviour reporting (reference: behaviour/reporter.go,
behaviour/peer_behaviour.go).

Reactors report typed peer behaviours to a single Reporter instead of
reaching into the Switch directly; the SwitchReporter translates bad
behaviours into StopPeerForError and good behaviours into addrbook/trust
credit. MockReporter records for tests.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

# behaviour kinds (reference: peer_behaviour.go:20-46)
BAD_MESSAGE = "bad_message"
MESSAGE_OUT_OF_ORDER = "message_out_of_order"
CONSENSUS_VOTE = "consensus_vote"
BLOCK_PART = "block_part"

_GOOD = {CONSENSUS_VOTE, BLOCK_PART}


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    kind: str
    reason: str = ""

    def is_good(self) -> bool:
        return self.kind in _GOOD


def bad_message(peer_id: str, reason: str) -> PeerBehaviour:
    return PeerBehaviour(peer_id, BAD_MESSAGE, reason)


def message_out_of_order(peer_id: str, reason: str) -> PeerBehaviour:
    return PeerBehaviour(peer_id, MESSAGE_OUT_OF_ORDER, reason)


def consensus_vote(peer_id: str, reason: str = "") -> PeerBehaviour:
    return PeerBehaviour(peer_id, CONSENSUS_VOTE, reason)


def block_part(peer_id: str, reason: str = "") -> PeerBehaviour:
    return PeerBehaviour(peer_id, BLOCK_PART, reason)


class SwitchReporter:
    """reference: behaviour/reporter.go:20 SwitchReporter."""

    def __init__(self, switch, trust_store=None):
        self._switch = switch
        self._trust = trust_store

    def report(self, b: PeerBehaviour) -> None:
        if self._trust is not None:
            m = self._trust.get_peer_trust_metric(b.peer_id)
            (m.good_events if b.is_good() else m.bad_events)()
        if b.is_good():
            return
        self._switch.stop_peer_by_id(b.peer_id, f"{b.kind}: {b.reason}")


class MockReporter:
    """reference: behaviour/reporter.go:47 MockReporter."""

    def __init__(self) -> None:
        self._mtx = threading.Lock()
        self._by_peer: dict[str, list[PeerBehaviour]] = {}

    def report(self, b: PeerBehaviour) -> None:
        with self._mtx:
            self._by_peer.setdefault(b.peer_id, []).append(b)

    def get_behaviours(self, peer_id: str) -> list[PeerBehaviour]:
        with self._mtx:
            return list(self._by_peer.get(peer_id, []))
