"""Peer, Transport, Switch, Reactor: the p2p service layer (reference:
p2p/switch.go, p2p/transport.go, p2p/peer.go, p2p/base_reactor.go:15-54).

Transport: TCP listen/dial -> SecretConnection -> NodeInfo handshake.
Peer: one MConnection; reactors receive (ch_id, peer, msg_bytes).
Switch: reactor registry, peer lifecycle, broadcast, dial/accept loops,
reconnect-to-persistent-peers.
"""

from __future__ import annotations

import random
import socket
import threading
import time

from typing import TYPE_CHECKING

from tendermint_tpu.encoding import proto
from tendermint_tpu.utils import faults, peerscore
from tendermint_tpu.p2p.connection import (
    ChannelDescriptor,
    MConnection,
    MConnectionProtocolError,
)
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo

if TYPE_CHECKING:
    from tendermint_tpu.p2p.secret_connection import SecretConnection


class P2PError(Exception):
    pass


class Reactor:
    """reference: p2p/base_reactor.go:15-54."""

    def __init__(self, name: str):
        self.name = name
        self.switch: "Switch | None" = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    def add_peer(self, peer: "Peer") -> None:
        pass

    def remove_peer(self, peer: "Peer", reason) -> None:
        pass

    def receive(self, ch_id: int, peer: "Peer", msg_bytes: bytes) -> None:
        pass

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass


class Peer:
    """reference: p2p/peer.go:23."""

    def __init__(self, conn: SecretConnection, node_info: NodeInfo,
                 channels: list[ChannelDescriptor], on_receive, on_error,
                 outbound: bool, persistent: bool = False,
                 socket_addr: str = "", send_rate: int = 5_120_000,
                 recv_rate: int = 5_120_000, local_id: str = "",
                 msg_rates: dict[int, float] | None = None,
                 on_rate_limited=None, tracer=None):
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr
        self._data: dict = {}
        self.mconn = MConnection(
            conn, channels,
            on_receive=lambda ch, msg: on_receive(ch, self, msg),
            on_error=lambda err: on_error(self, err),
            send_rate=send_rate, recv_rate=recv_rate,
            local_id=local_id, remote_id=node_info.node_id,
            msg_rates=msg_rates,
            on_rate_limited=(lambda ch: on_rate_limited(self, ch))
            if on_rate_limited is not None else None,
            tracer=tracer,
        )

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def start(self) -> None:
        self.mconn.start()

    def stop(self) -> None:
        self.mconn.stop()

    def send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.send(ch_id, msg)

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(ch_id, msg)

    def set(self, key: str, value) -> None:
        self._data[key] = value

    def get(self, key: str):
        return self._data.get(key)

    def __repr__(self) -> str:
        return f"Peer{{{self.id[:12]} {'out' if self.outbound else 'in'}}}"


class Transport:
    """MultiplexTransport equivalent (reference: p2p/transport.go)."""

    def __init__(self, node_key: NodeKey, node_info: NodeInfo,
                 handshake_timeout_s: float = 20.0, dial_timeout_s: float = 3.0):
        self.node_key = node_key
        self.node_info = node_info
        self.handshake_timeout_s = handshake_timeout_s
        self.dial_timeout_s = dial_timeout_s
        self._listener: socket.socket | None = None
        # overload-resilience hooks (set by the owning Switch): a banned
        # peer is refused right after the handshake identifies it, on the
        # accept AND dial sides alike; an evil handshake (claimed id not
        # matching the authenticated key) is scored before the teardown
        self.ban_checker = None        # fn(node_id) -> bool
        self.on_evil_handshake = None  # fn(authenticated_node_id)

    def listen(self, addr: str) -> str:
        host, port = _split_addr(addr)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(64)
        self._listener = s
        actual = s.getsockname()
        self.node_info.listen_addr = f"tcp://{actual[0]}:{actual[1]}"
        return self.node_info.listen_addr

    def accept(self) -> tuple[SecretConnection, NodeInfo, str]:
        if self._listener is None:
            raise P2PError("transport not listening")
        raw, addr = self._listener.accept()
        return self._upgrade(raw, f"{addr[0]}:{addr[1]}")

    def dial(self, addr: str) -> tuple[SecretConnection, NodeInfo, str]:
        # peer-id context: an "id@host:port" addr names the remote, so a
        # nemesis partition can refuse dials across the cut
        faults.fire("p2p.dial", local=self.node_info.node_id,
                    remote=addr.split("@", 1)[0] if "@" in addr else "")
        host, port = _split_addr(addr)
        raw = socket.create_connection((host, port), timeout=self.dial_timeout_s)
        return self._upgrade(raw, f"{host}:{port}")

    def _upgrade(self, raw: socket.socket, addr: str):
        # Deferred: SecretConnection needs the optional `cryptography`
        # package; the switch (backoff logic, registry) must import without
        # it so hosts lacking the dep can still run non-p2p subsystems.
        from tendermint_tpu.p2p.secret_connection import SecretConnection

        raw.settimeout(self.handshake_timeout_s)
        conn = SecretConnection(raw, self.node_key.priv_key)
        # NodeInfo exchange (reference: transport.go handshake)
        conn.write(proto.delimited(self.node_info.marshal()))
        buf = conn.read_msg()
        while True:
            try:
                body, _ = proto.parse_delimited(buf)
                break
            except ValueError:
                buf += conn.read_msg()
        peer_info = NodeInfo.unmarshal(body)
        peer_info.validate_basic()
        # The authenticated ed25519 key must match the claimed node ID.
        derived = conn.remote_pub_key.address().hex()
        if derived != peer_info.node_id:
            if self.on_evil_handshake is not None:
                # score the AUTHENTICATED identity: the claimed one is
                # whatever the liar chose to type
                self.on_evil_handshake(derived)
            raise P2PError(
                f"peer ID mismatch: claimed {peer_info.node_id}, authenticated {derived}"
            )
        if self.ban_checker is not None and self.ban_checker(peer_info.node_id):
            raise P2PError(f"peer {peer_info.node_id[:12]} is banned")
        raw.settimeout(None)
        return conn, peer_info, addr

    def close(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


# Persistent-peer redial backoff (reference: p2p/switch.go:768
# reconnectToPeer): first retry fast, then exponential with jitter so a
# fleet of nodes redialing one restarting peer never synchronizes into a
# dial storm. Capped low enough that a peer coming back is found quickly.
RECONNECT_BASE_S = 0.5
RECONNECT_MAX_S = 10.0
RECONNECT_JITTER = 0.2


def reconnect_backoff_s(attempt: int, rng=random) -> float:
    """Delay before redial number ``attempt`` (0-based: the delay AFTER the
    attempt-th consecutive failure), exponentially grown and jittered.
    The exponent is clamped BEFORE exponentiation: 2.0**1024 overflows a
    float, and a peer down for hours must not kill the reconnect thread."""
    base = min(RECONNECT_BASE_S * (2.0 ** min(attempt, 16)), RECONNECT_MAX_S)
    return base * (1.0 + RECONNECT_JITTER * rng.random())


class Switch:
    """reference: p2p/switch.go:65."""

    def __init__(self, transport: Transport, logger=None,
                 max_inbound: int = 40, max_outbound: int = 10,
                 send_rate: int = 5_120_000, recv_rate: int = 5_120_000,
                 scoreboard: peerscore.PeerScoreBoard | None = None,
                 msg_rates: dict[int, float] | None = None):
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        self.transport = transport
        # Overload-resilience plane (docs/OVERLOAD.md): one scoreboard per
        # switch — in-process mesh nodes must sanction independently. The
        # board decides sanctions; this switch enforces them (disconnect,
        # ban = teardown + dial/accept refusal until expiry).
        self.scoreboard = (scoreboard if scoreboard is not None
                           else peerscore.PeerScoreBoard(logger=logger))
        self.scoreboard.on_ban.append(self._on_peer_banned)
        self.scoreboard.on_disconnect.append(self._on_peer_sanctioned)
        self.msg_rates = dict(msg_rates) if msg_rates else {}
        transport.ban_checker = self.scoreboard.is_banned
        transport.on_evil_handshake = (
            lambda nid: self.scoreboard.record(nid, "evil_handshake"))
        self.reactors: dict[str, Reactor] = {}
        self._channels: list[ChannelDescriptor] = []
        self._reactors_by_ch: dict[int, Reactor] = {}
        self.peers: dict[str, Peer] = {}
        self._peers_mtx = threading.RLock()
        self._running = False
        self.logger = logger
        self.max_inbound = max_inbound
        self.max_outbound = max_outbound
        self._persistent_addrs: list[str] = []
        self._accept_thread: threading.Thread | None = None
        self._reconnect_thread: threading.Thread | None = None
        # flight recorder (utils/trace.py): node wiring installs the node's
        # tracer BEFORE start(); every peer connection built afterwards
        # records its per-channel send/recv events there
        self.tracer = None
        # Redial backoff state, instance-level so kick_reconnect() can wipe
        # it (a nemesis heal must not wait out the clamped max backoff
        # accumulated while the partition blocked every dial).
        self._reconnect_attempts: dict[str, int] = {}
        self._reconnect_next_try: dict[str, float] = {}

    # --- registry ----------------------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        for d in reactor.get_channels():
            if d.id in self._reactors_by_ch:
                raise P2PError(f"channel {d.id:#x} already registered")
            self._channels.append(d)
            self._reactors_by_ch[d.id] = reactor
        self.reactors[name] = reactor
        reactor.switch = self
        self.transport.node_info.channels = bytes(sorted(self._reactors_by_ch))
        return reactor

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._running = True
        for r in self.reactors.values():
            r.on_start()
        if self.transport._listener is not None:
            self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
            self._accept_thread.start()
        self._reconnect_thread = threading.Thread(target=self._reconnect_loop, daemon=True)
        self._reconnect_thread.start()
        # A healed partition should reconnect promptly, not after the max
        # backoff the cut accumulated (lazy import: nemesis is pure stdlib,
        # but keep the switch importable standalone all the same).
        from tendermint_tpu.utils import nemesis

        nemesis.PLANE.on_heal.append(self.kick_reconnect)

    def stop(self) -> None:
        self._running = False
        from tendermint_tpu.utils import nemesis

        try:
            nemesis.PLANE.on_heal.remove(self.kick_reconnect)
        except ValueError:
            pass
        for r in self.reactors.values():
            r.on_stop()
        with self._peers_mtx:
            peers = list(self.peers.values())
        for p in peers:
            self.stop_peer_for_error(p, "switch stopping")
        self.transport.close()

    # --- dialing / accepting -----------------------------------------------

    def dial_peer(self, addr: str, persistent: bool = False) -> Peer | None:
        node_id = addr.split("@", 1)[0] if "@" in addr else ""
        if node_id and self.scoreboard.is_banned(node_id):
            # refuse BEFORE the socket opens: a banned peer's redial must
            # cost us nothing (the transport-side ban_checker still covers
            # addresses dialed without an id prefix)
            if self.logger:
                self.logger.info("refusing dial to banned peer", addr=addr)
            return None
        try:
            conn, peer_info, sock_addr = self.transport.dial(addr)
            return self._add_peer(conn, peer_info, outbound=True,
                                  persistent=persistent, socket_addr=addr)
        except Exception as e:  # noqa: BLE001
            if self.logger:
                self.logger.info("dial failed", addr=addr, err=e)
            return None

    def add_persistent_peers(self, addrs: list[str]) -> None:
        self._persistent_addrs.extend(a for a in addrs if a)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, peer_info, sock_addr = self.transport.accept()
            except Exception:  # noqa: BLE001
                if not self._running:
                    return
                continue
            n_in = sum(1 for p in self.peers.values() if not p.outbound)
            if n_in >= self.max_inbound:
                conn.close()
                continue
            try:
                self._add_peer(conn, peer_info, outbound=False, socket_addr=sock_addr)
            except Exception:  # noqa: BLE001
                conn.close()

    def kick_reconnect(self) -> None:
        """Forget all redial backoff state so every missing persistent peer
        is retried on the next pass (≤0.25 s). Called on nemesis heal: a
        peer redialed throughout a long partition sits at the clamped max
        backoff, and a healed link must not wait that out."""
        self._reconnect_attempts.clear()
        self._reconnect_next_try.clear()

    def _reconnect_loop(self) -> None:
        """Redial missing persistent peers with exponential backoff +
        jitter; a successful dial (or the peer appearing inbound) resets
        that address's schedule."""
        while self._running:
            try:
                if self._persistent_addrs:
                    self._reconnect_pass(self._reconnect_attempts,
                                         self._reconnect_next_try)
            except Exception as e:  # noqa: BLE001 - the redial thread must
                # survive anything; losing it silently strands every
                # persistent peer for the rest of the process lifetime
                if self.logger:
                    self.logger.error("reconnect pass failed", err=e)
            # nothing to redial -> idle slowly: 50+ in-process switches
            # (the scenario fabric) each waking 4x/s add up on one core
            time.sleep(0.25 if self._persistent_addrs else 1.0)

    def _reconnect_pass(self, attempts: dict[str, int],
                        next_try: dict[str, float]) -> None:
        now = time.monotonic()
        for addr in list(self._persistent_addrs):
            node_id = addr.split("@")[0] if "@" in addr else None
            if node_id and self.scoreboard.is_banned(node_id):
                # don't burn backoff schedule on a banned persistent peer;
                # when the ban expires the address is retried immediately
                attempts.pop(addr, None)
                next_try.pop(addr, None)
                continue
            have = node_id in self.peers if node_id else any(
                p.socket_addr.endswith(addr) for p in self.peers.values()
            )
            if have:
                attempts.pop(addr, None)
                next_try.pop(addr, None)
                continue
            if now < next_try.get(addr, 0.0):
                continue
            if self.dial_peer(addr, persistent=True) is not None:
                # reset the attempt counter on success: the NEXT outage of
                # this link starts its backoff from scratch instead of
                # inheriting the clamped max from the previous one
                attempts.pop(addr, None)
                next_try.pop(addr, None)
            else:
                k = attempts.get(addr, 0)
                attempts[addr] = k + 1
                next_try[addr] = time.monotonic() + reconnect_backoff_s(k)

    def _add_peer(self, conn, peer_info: NodeInfo, outbound: bool,
                  persistent: bool = False, socket_addr: str = "") -> Peer:
        self.transport.node_info.compatible_with(peer_info)
        if peer_info.node_id == self.transport.node_info.node_id:
            conn.close()
            raise P2PError("connected to self")
        if self.scoreboard.is_banned(peer_info.node_id):
            # inbound rejection + the in-process mesh seam: however the
            # connection reached us (accept loop, test socketpair), a
            # banned identity never becomes a Peer
            conn.close()
            raise P2PError(f"peer {peer_info.node_id[:12]} is banned")
        with self._peers_mtx:
            if peer_info.node_id in self.peers:
                conn.close()
                raise P2PError("duplicate peer")
            peer = Peer(conn, peer_info, self._channels, self._on_receive,
                        self._on_peer_error, outbound, persistent, socket_addr,
                        send_rate=self.send_rate, recv_rate=self.recv_rate,
                        local_id=self.transport.node_info.node_id,
                        msg_rates=self.msg_rates,
                        on_rate_limited=self._on_rate_limited,
                        tracer=self.tracer)
            self.peers[peer.id] = peer
        # Reactors attach their per-peer state (and queue their hello
        # messages) BEFORE the connection starts reading: bytes the remote
        # already sent — its status, its NewRoundStep — must not reach a
        # reactor whose add_peer hasn't run yet, or a peer that never
        # re-announces (parked at a height) stays invisible forever
        # (reference: the InitPeer/AddPeer split of p2p/switch.go:840).
        for r in self.reactors.values():
            r.add_peer(peer)
        peer.start()
        return peer

    # --- peer events -------------------------------------------------------

    def _on_receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        if self.scoreboard.is_banned(peer.id):
            # post-ban traffic never reaches a reactor (the drain must not
            # process a banned peer's in-flight backlog); tear down in case
            # the ban callback raced the delivery
            self.stop_peer_for_error(peer, "peer is banned")
            return
        reactor = self._reactors_by_ch.get(ch_id)
        if reactor is None:
            self.scoreboard.record(peer.id, "bad_message")
            self.stop_peer_for_error(peer, f"unknown channel {ch_id:#x}")
            return
        try:
            reactor.receive(ch_id, peer, msg_bytes)
        except Exception as e:  # noqa: BLE001
            # Codec-shaped failures (ValueError from proto parsing /
            # unmarshal validation) are the PEER's malformed payload:
            # score them so a redial-and-repeat loop escalates to a ban
            # instead of free disconnect cycles. Anything else —
            # KeyError/IndexError included, the classic shapes of a
            # node-local reactor bug on valid input — tears the peer
            # down (the pre-existing contract) without scoring: our own
            # bug must not progressively ban the honest peer set.
            if isinstance(e, ValueError):
                self.scoreboard.record(peer.id, "bad_message")
            self.stop_peer_for_error(peer, e)

    def _on_peer_error(self, peer: Peer, err) -> None:
        if isinstance(err, MConnectionProtocolError):
            # framing/capacity violations (oversized message, bad varint,
            # unknown mconnection channel) are the peer's doing; a plain
            # MConnectionError (socket EOF) is just the network — scoring
            # it would ban honest peers across partition/reconnect churn
            self.scoreboard.record(peer.id, "oversized_message")
        self.stop_peer_for_error(peer, err)

    def _on_rate_limited(self, peer: Peer, ch_id: int) -> None:
        """An over-limit delivery was discarded by the connection's token
        bucket: count + score it (enough of these escalate to a ban)."""
        self.scoreboard.count_rate_limited(peer.id, f"{ch_id:#x}")
        self.scoreboard.record(peer.id, "rate_limited")

    def _on_peer_banned(self, peer_id: str, until: float) -> None:
        self.stop_peer_by_id(peer_id, "banned for misbehavior")

    def _on_peer_sanctioned(self, peer_id: str, reason: str) -> None:
        self.stop_peer_by_id(peer_id, reason)

    def stop_peer_by_id(self, peer_id: str, reason) -> bool:
        """Public stop-by-id for behaviour reporters etc.; returns False when
        the peer is already gone."""
        with self._peers_mtx:
            peer = self.peers.get(peer_id)
        if peer is None:
            return False
        self.stop_peer_for_error(peer, reason)
        return True

    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        """reference: p2p/switch.go StopPeerForError."""
        with self._peers_mtx:
            if self.peers.get(peer.id) is not peer:
                return
            del self.peers[peer.id]
        peer.stop()
        for r in self.reactors.values():
            try:
                r.remove_peer(peer, reason)
            except Exception:  # noqa: BLE001
                pass

    # --- broadcast ---------------------------------------------------------

    def broadcast(self, ch_id: int, msg: bytes) -> None:
        with self._peers_mtx:
            peers = list(self.peers.values())
        for p in peers:
            p.try_send(ch_id, msg)

    def num_peers(self) -> tuple[int, int]:
        with self._peers_mtx:
            out = sum(1 for p in self.peers.values() if p.outbound)
            return out, len(self.peers) - out


def _split_addr(addr: str) -> tuple[str, int]:
    a = addr
    if "://" in a:
        a = a.split("://", 1)[1]
    if "@" in a:
        a = a.split("@", 1)[1]
    host, port = a.rsplit(":", 1)
    return host, int(port)
