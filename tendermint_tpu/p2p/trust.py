"""Peer trust metric (reference: p2p/trust/metric.go, store.go).

Tracks per-peer behavior as a weighted mix of recent and historical
good/bad event ratios:

    value = weight_r * R + weight_h * H * derivative_gain

where R is the current-interval ratio, H a rolling history average, and a
negative-trend derivative dampens flapping peers (metric.go:120 design
notes). The store keys metrics by peer ID and prunes on peer removal;
Switch users ban peers whose value drops below a threshold.
"""

from __future__ import annotations

import threading
import time

DEFAULT_INTERVAL_S = 10.0
MAX_HISTORY = 16
WEIGHT_R = 0.8
WEIGHT_H = 0.2


class TrustMetric:
    """reference: p2p/trust/metric.go:63 TrustMetric."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S):
        self._interval = interval_s
        self._mtx = threading.Lock()
        self._good = 0.0
        self._bad = 0.0
        self._history: list[float] = []
        self._interval_start = time.monotonic()

    def good_events(self, n: int = 1) -> None:
        with self._mtx:
            self._tick_locked()
            self._good += n

    def bad_events(self, n: int = 1) -> None:
        with self._mtx:
            self._tick_locked()
            self._bad += n

    def _tick_locked(self) -> None:
        now = time.monotonic()
        while now - self._interval_start >= self._interval:
            self._history.append(self._ratio_locked())
            if len(self._history) > MAX_HISTORY:
                self._history.pop(0)
            self._good = self._bad = 0.0
            self._interval_start += self._interval

    def _ratio_locked(self) -> float:
        total = self._good + self._bad
        return self._good / total if total > 0 else 1.0

    def trust_value(self) -> float:
        """[0, 1]; 1 = fully trusted (reference TrustValue)."""
        with self._mtx:
            self._tick_locked()
            r = self._ratio_locked()
            h = (sum(self._history) / len(self._history)
                 if self._history else r)
            v = WEIGHT_R * r + WEIGHT_H * h
            # negative-trend damping: falling ratio vs history drags trust
            # down faster than it recovers (metric.go derivative term)
            d = r - h
            if d < 0:
                v += WEIGHT_R * d
            return max(0.0, min(1.0, v))

    def trust_score(self) -> int:
        """0-100 integer form (reference TrustScore)."""
        return int(round(self.trust_value() * 100))


class TrustMetricStore:
    """reference: p2p/trust/store.go TrustMetricStore."""

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S):
        self._interval = interval_s
        self._mtx = threading.Lock()
        self._metrics: dict[str, TrustMetric] = {}

    def get_peer_trust_metric(self, peer_id: str) -> TrustMetric:
        with self._mtx:
            m = self._metrics.get(peer_id)
            if m is None:
                m = self._metrics[peer_id] = TrustMetric(self._interval)
            return m

    def peer_disconnected(self, peer_id: str) -> None:
        with self._mtx:
            self._metrics.pop(peer_id, None)

    def size(self) -> int:
        with self._mtx:
            return len(self._metrics)
