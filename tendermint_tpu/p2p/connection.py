"""MConnection: multiplexes priority channels over one SecretConnection
(reference: p2p/conn/connection.go:78, proto/tendermint/p2p/conn.proto).

Wire format: varint-delimited Packet protos over the encrypted stream.
  Packet { oneof sum: PacketPing = 1 | PacketPong = 2 | PacketMsg = 3 }
  PacketMsg { channel_id = 1; eof = 2; data = 3 }
Messages larger than the packet payload size are split across PacketMsgs and
reassembled at eof. Channel scheduling is priority-weighted ratio picking
like the reference's sendRoutine (connection.go:320-420).
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.encoding import proto
from tendermint_tpu.utils import faults
from tendermint_tpu.utils.flowrate import Monitor

MAX_PACKET_MSG_PAYLOAD_SIZE = 1024
PING_INTERVAL_S = 20.0
PONG_TIMEOUT_S = 45.0
FLUSH_THROTTLE_S = 0.01
MAX_MSG_SIZE = 10 * 1024 * 1024
# reference: config SendRate/RecvRate default 5120000 B/s (connection.go:
# flow-controlled via libs/flowrate Monitor.Limit)
DEFAULT_SEND_RATE = 5_120_000
DEFAULT_RECV_RATE = 5_120_000


class MConnectionError(Exception):
    pass


class MConnectionProtocolError(MConnectionError):
    """The PEER violated the wire protocol (oversized packet/message, bad
    framing, unknown channel) — scoreable misbehavior, unlike a plain
    MConnectionError (socket EOF/teardown), which is just the network."""


@dataclass
class ChannelDescriptor:
    """reference: p2p/conn/connection.go:560-600."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = 22020096


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.send_queue: queue.Queue = queue.Queue(maxsize=desc.send_queue_capacity)
        self.sending: bytes | None = None
        self.sent_pos = 0
        self.recently_sent = 0
        self.recving = bytearray()

    def is_send_pending(self) -> bool:
        return self.sending is not None or not self.send_queue.empty()

    def next_packet(self) -> tuple[bytes, bool]:
        if self.sending is None:
            self.sending = self.send_queue.get_nowait()
            self.sent_pos = 0
        chunk = self.sending[self.sent_pos : self.sent_pos + MAX_PACKET_MSG_PAYLOAD_SIZE]
        self.sent_pos += len(chunk)
        eof = self.sent_pos >= len(self.sending)
        if eof:
            self.sending = None
            self.sent_pos = 0
        self.recently_sent += len(chunk)
        return chunk, eof


class MConnection:
    """on_receive(ch_id, msg_bytes); on_error(err) when the conn dies."""

    def __init__(self, conn, channels: list[ChannelDescriptor], on_receive,
                 on_error=None, send_rate: int = DEFAULT_SEND_RATE,
                 recv_rate: int = DEFAULT_RECV_RATE,
                 local_id: str = "", remote_id: str = "",
                 msg_rates: dict[int, float] | None = None,
                 on_rate_limited=None, tracer=None):
        self._conn = conn
        # peer-id context for the link-scoped fault plane (utils/nemesis.py):
        # which directed link this connection is, so a partition can cut
        # exactly the messages crossing it
        self._local_id = local_id
        self._remote_id = remote_id
        self._channels = {d.id: _Channel(d) for d in channels}
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_event = threading.Event()
        self._running = False
        self._stopped = False  # terminal: stop() or a transport error
        self._send_thread: threading.Thread | None = None
        self._recv_thread: threading.Thread | None = None
        self._last_recv = time.monotonic()
        self._recv_stream = b""
        # flow accounting + throttling (reference: connection.go:78
        # sendMonitor/recvMonitor; Limit() applied in sendSomePacketMsgs)
        self.send_monitor = Monitor()
        self.recv_monitor = Monitor()
        self._send_rate = send_rate
        self._recv_rate = recv_rate
        # Per-peer per-channel inbound message ceilings (msgs/s token
        # buckets, docs/OVERLOAD.md): over-limit deliveries are reported
        # to on_rate_limited(ch_id) — scored by the switch — instead of
        # being processed.
        self._rate_limiter = None
        if msg_rates:
            from tendermint_tpu.utils.peerscore import ChannelRateLimiter

            self._rate_limiter = ChannelRateLimiter(msg_rates)
        self._on_rate_limited = on_rate_limited
        # flight recorder (utils/trace.py): per-channel send/recv events
        # land in the owning node's tracer; None = untraced
        self._tracer = tracer

    def start(self) -> None:
        self._running = True
        self._send_thread = threading.Thread(target=self._send_routine, daemon=True)
        self._recv_thread = threading.Thread(target=self._recv_routine, daemon=True)
        self._send_thread.start()
        self._recv_thread.start()

    def stop(self) -> None:
        self._stopped = True
        self._running = False
        self._send_event.set()
        self._conn.close()

    # --- sending -----------------------------------------------------------

    def send(self, ch_id: int, msg: bytes, block: bool = True) -> bool:
        """Queue a message on a channel (reference: connection.go:250-290).
        Queuing is allowed BEFORE start(): the switch attaches reactors
        (which send their hello messages — status, NewRoundStep) before it
        starts the connection, so no peer can deliver bytes to a reactor
        that hasn't attached its per-peer state yet; the send routine
        drains the queues once start() runs."""
        ch = self._channels.get(ch_id)
        if ch is None or self._stopped:
            return False
        try:
            verdict = faults.link_outcome("p2p.send", self._local_id,
                                          self._remote_id, channel=ch_id)
        except faults.FaultDisconnect as e:
            # documented disconnect semantics: a transport-style teardown
            # (peer removal + reconnect), never an exception into the
            # arbitrary sending thread (gossip loops have no handler)
            self._die(e)
            return False
        if verdict == "drop":
            return True  # loss after send: the caller sees success
        try:
            ch.send_queue.put(msg, block=block, timeout=10 if block else None)
        except queue.Full:
            return False
        tr = self._tracer
        if tr is not None and tr.enabled:
            tr.mark("p2p.send", channel=f"{ch_id:#x}", bytes=len(msg))
        if verdict == "dup":
            try:
                ch.send_queue.put(msg, block=False)
            except queue.Full:
                pass  # duplication is best-effort; the original made it in
        elif verdict == "flood":
            # byzantine amplification (nemesis flood action): seeded
            # corrupted copies ride along with the real message — invalid
            # signatures / unparseable junk the RECEIVER must score away
            from tendermint_tpu.utils import nemesis

            for junk in nemesis.PLANE.flood_payloads(
                    self._local_id, self._remote_id, ch_id, msg):
                try:
                    ch.send_queue.put(junk, block=False)
                except queue.Full:
                    break  # amplification is best-effort
        self._send_event.set()
        return True

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.send(ch_id, msg, block=False)

    def _pick_channel(self) -> _Channel | None:
        """Least ratio of recentlySent/priority (reference:
        connection.go:380-420 sendPacketMsg)."""
        best, least = None, None
        for ch in self._channels.values():
            if not ch.is_send_pending():
                continue
            ratio = ch.recently_sent / ch.desc.priority
            if least is None or ratio < least:
                least = ratio
                best = ch
        return best

    def _send_routine(self) -> None:
        last_ping = time.monotonic()
        try:
            while self._running:
                ch = self._pick_channel()
                if ch is None:
                    if time.monotonic() - last_ping > PING_INTERVAL_S:
                        self._write_packet(proto.Writer().message(1, b"", always=True).out())
                        last_ping = time.monotonic()
                    fired = self._send_event.wait(timeout=0.05)
                    if fired:
                        self._send_event.clear()
                    # decay recentlySent (flowrate stand-in)
                    for c in self._channels.values():
                        c.recently_sent = int(c.recently_sent * 0.8)
                    continue
                # Rate limit before pulling the packet (reference:
                # sendSomePacketMsgs -> sendMonitor.Limit(maxPacketMsgSize,
                # SendRate, true)).
                self.send_monitor.limit(MAX_PACKET_MSG_PAYLOAD_SIZE,
                                        self._send_rate, block=True)
                chunk, eof = ch.next_packet()
                pm = (
                    proto.Writer()
                    .varint(1, ch.desc.id)
                    .bool(2, eof)
                    .bytes(3, chunk)
                    .out()
                )
                packet = proto.Writer().message(3, pm, always=True).out()
                self._write_packet(packet)
                self.send_monitor.update(len(packet))
        except Exception as e:  # noqa: BLE001
            self._die(e)

    def _write_packet(self, packet: bytes) -> None:
        self._conn.write(proto.delimited(packet))

    # --- receiving ---------------------------------------------------------

    def _read_delimited(self) -> bytes:
        # varint length then body, over the stream-oriented secret conn
        ln = 0
        shift = 0
        while True:
            b = self._read_bytes(1)[0]
            ln |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise MConnectionProtocolError("bad packet length varint")
        if ln > MAX_MSG_SIZE:
            raise MConnectionProtocolError(f"packet too big: {ln}")
        return self._read_bytes(ln)

    def _read_bytes(self, n: int) -> bytes:
        while len(self._recv_stream) < n:
            # Rate limit before pulling bytes off the wire, symmetrical to
            # the send side (reference: connection.go recvRoutine ->
            # recvMonitor.Limit(maxMsgPacketTotalSize, RecvRate, true)):
            # a flooding sender backs up into ITS socket buffer instead of
            # monopolizing our reactor threads. Blocking limit() returns
            # at least 1 allowed byte.
            want = self.recv_monitor.limit(65536, self._recv_rate, block=True)
            chunk = self._conn.read(max(want, 1))
            if not chunk:
                raise MConnectionError("connection closed")
            self._recv_stream += chunk
            self.recv_monitor.update(len(chunk))
        out = self._recv_stream[:n]
        self._recv_stream = self._recv_stream[n:]
        return out

    def _recv_routine(self) -> None:
        try:
            while self._running:
                packet = self._read_delimited()
                f = proto.fields(packet)
                if 1 in f:  # ping -> pong
                    self._write_packet(proto.Writer().message(2, b"", always=True).out())
                elif 2 in f:  # pong
                    self._last_recv = time.monotonic()
                elif 3 in f:
                    pf = proto.fields(f[3][-1])
                    ch_id = proto.as_sint64(pf.get(1, [0])[-1])
                    eof = bool(pf.get(2, [0])[-1])
                    data = pf.get(3, [b""])[-1]
                    ch = self._channels.get(ch_id)
                    if ch is None:
                        raise MConnectionProtocolError(f"unknown channel {ch_id:#x}")
                    ch.recving += data
                    if len(ch.recving) > ch.desc.recv_message_capacity:
                        raise MConnectionProtocolError("received message exceeds capacity")
                    if eof:
                        msg = bytes(ch.recving)
                        ch.recving = bytearray()
                        # per-channel message ceiling: an over-limit
                        # delivery is scored (via the switch callback),
                        # never processed — the channel's token bucket is
                        # the SEDA admission gate in front of the reactors
                        if (self._rate_limiter is not None
                                and not self._rate_limiter.allow(ch_id)):
                            if self._on_rate_limited is not None:
                                self._on_rate_limited(ch_id)
                            continue
                        # drop skips delivery; dup delivers twice;
                        # disconnect raises into _die, which tears the
                        # peer down like a transport error
                        verdict = faults.link_outcome(
                            "p2p.recv", self._local_id, self._remote_id,
                            channel=ch_id)
                        if verdict != "drop":
                            tr = self._tracer
                            if tr is not None and tr.enabled:
                                # the span times the reactor's receive
                                # handler — where per-message Python cost
                                # (the 100-node wall) actually goes
                                with tr.span("p2p.recv",
                                             channel=f"{ch_id:#x}",
                                             bytes=len(msg)):
                                    self._on_receive(ch_id, msg)
                            else:
                                self._on_receive(ch_id, msg)
                            if verdict == "dup":
                                self._on_receive(ch_id, msg)
                self._last_recv = time.monotonic()
        except Exception as e:  # noqa: BLE001
            self._die(e)

    def _die(self, err: Exception) -> None:
        # gates on the terminal flag, not _running: a fatal fault on a
        # message queued BEFORE start() must still tear the peer down
        if self._stopped:
            return
        self._stopped = True
        self._running = False
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001
            pass
        if self._on_error is not None:
            self._on_error(err)
