"""Headless fast-sync replay: drive the verify-ahead pipeline
(blockchain/pipeline.py) over pre-built blocks with stub persistence — no
p2p, no disk. The ONE copy of the chained-block builder (real part-set
block IDs in every LastCommit, what the fast-sync verify checks) and the
minimal reactor surface VerifyAheadPipeline drives, shared by the bench
correctness gate (bench.py config_fastsync) and the pipeline tests
(tests/test_fastsync_pipeline.py, tests/test_perf_gate.py) so the two can
never drift."""

from __future__ import annotations

import hashlib
import types as pytypes

from tendermint_tpu.blockchain.reactor import BlockPool
from tendermint_tpu.types.block import Block, Commit, CommitSig, Data, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote


def signed_commit(chain_id, vals, privs, height, bid, ts, round_=1):
    """One precommit per validator over the canonical sign bytes."""
    sigs = []
    for i, (p, v) in enumerate(zip(privs, vals.validators)):
        vote = Vote(type=PRECOMMIT_TYPE, height=height, round=round_,
                    block_id=bid, timestamp=ts, validator_address=v.address,
                    validator_index=i)
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                              p.sign(vote.sign_bytes(chain_id))))
    return Commit(height=height, round=round_, block_id=bid, signatures=sigs)


def make_chain(chain_id, n, vals, privs, txs_for=None):
    """n chained blocks with real part-set block IDs in each LastCommit —
    what the fast-sync verify checks. `txs_for(height) -> list[bytes]`
    optionally fills each block's Data (the batched-execution bench and
    replay-equivalence tests feed full blocks through here; empty blocks
    otherwise, as before)."""
    blocks, prev_commit, prev_bid = [], None, BlockID()
    for h in range(1, n + 1):
        header = Header(chain_id=chain_id, height=h,
                        time=Time(1_700_000_000 + h, 0),
                        last_block_id=prev_bid, validators_hash=vals.hash(),
                        next_validators_hash=vals.hash(),
                        proposer_address=vals.validators[0].address)
        data = Data(txs=list(txs_for(h))) if txs_for is not None else Data()
        block = Block(header=header, data=data, last_commit=prev_commit)
        bhash = block.hash()
        parts = PartSet.from_data(block.marshal())
        bid = BlockID(hash=bhash, part_set_header=parts.header())
        prev_commit = signed_commit(chain_id, vals, privs, h, bid,
                                    Time(header.time.seconds, 0))
        prev_bid = bid
        blocks.append(block)
    return blocks


class ReplayCtx:
    """Minimal reactor surface for VerifyAheadPipeline: a real BlockPool,
    stub store/executor, app hash chained over accepted block IDs (two
    replays accepting the same blocks in the same order agree)."""

    def __init__(self, vals, chain_id, app=None):
        self.pool = BlockPool(1)
        self.state = pytypes.SimpleNamespace(validators=vals,
                                             chain_id=chain_id)
        self.applied: list[int] = []
        self.punished: list[str] = []
        self.app_hash = b"\x00" * 32
        self.app = app
        outer = self

        class _Store:
            def save_block(self, block, parts, seen_commit):
                pass

        class _Exec:
            def apply_block(self, state, block_id, block, commit_pending=None):
                outer.applied.append(block.header.height)
                if outer.app is None:
                    outer.app_hash = hashlib.sha256(
                        outer.app_hash + block_id.hash).digest()
                else:
                    # app-backed replay: the block's txs run through the
                    # shared deliver engine (docs/EXECUTION.md), so the
                    # bench / equivalence tests exercise the same batched
                    # vs serial paths the real BlockExecutor does
                    from tendermint_tpu.abci import types as abci
                    from tendermint_tpu.state.execution import deliver_block_txs

                    outer.app.begin_block(abci.RequestBeginBlock(
                        hash=block.hash() or b"", header=block.header))
                    deliver_block_txs(outer.app, block.data.txs)
                    outer.app.end_block(
                        abci.RequestEndBlock(height=block.header.height))
                    res = outer.app.commit()
                    outer.app_hash = hashlib.sha256(
                        outer.app_hash + block_id.hash + res.data).digest()
                return state, 0

        self.block_store = _Store()
        self.block_exec = _Exec()

    def _punish_invalid(self, height, e):
        bad = self.pool.redo_request(height)
        bad2 = self.pool.redo_request(height + 1)
        self.punished.extend(sorted({bad, bad2} - {None}))
