"""Verify-ahead: the cross-decision commit-verify pipeline for fast sync.

BENCH r05: the host<->device round trip (`sync_floor_ms` ~104 ms) dominates
every verify decision — a 20,480-sig commit costs 151 ms of which ~104 ms is
the floor, marginal cost 4.34 us/sig. The serial fast-sync loop
(blockchain/reactor.py `_try_sync`, v1.py `try_process_block`) pays that
floor once per block, serialized with block save/apply, so throughput is
floor-bound no matter how fast the kernel gets.

This module lifts the chunk-level pipelining of ops/ed25519_pallas
(dispatch_items_pipelined, _start_host_copy) to DECISION granularity:

  * up to depth-K blocks' commit verifications are dispatched
    (`ValidatorSet.verify_commit_light_async`) while block h is being
    saved/applied;
  * readbacks of every in-flight decision are batched into ONE
    `jax.device_get` (crypto_batch.prefetch), so K decisions pay one sync
    floor instead of K;
  * decisions RESOLVE strictly in height order, and each resolve replays
    the exact serial accept/reject procedure — accept/reject and error
    attribution are byte-identical to the serial loop.

Failure semantics (identical to the serial path): a failed resolve at
height h discards ALL speculative in-flight work, redoes the requests for
h and h+1, and punishes the two sending peers — exactly what the serial
loop does at the same height with the same pool contents. Speculation is
also discarded whenever dispatch-time inputs went stale: the pool's blocks
at the entry's heights changed (peer churn, redo), or the validator set
hash changed after an apply (validator-set updates mid-sync). Discarded
work is re-dispatched against current reality, so the DECISIONS can never
drift from serial — only wasted device cycles are at stake.

Fault sites are preserved inside the pipeline: each speculative dispatch
still passes through `faults.fire("ops.ed25519.device")` (and the sr25519
twin) inside ops dispatch_batch, behind the circuit breaker
(ops/breaker.py) — an injected or real device failure degrades that
dispatch to the host path within the same call and the pipeline's
decisions are unchanged.

`TM_TPU_VERIFY_AHEAD` sets the depth (default 4; 1 = serial behavior,
one decision dispatched and resolved at a time). See docs/PIPELINE.md.

Device-bound speculative dispatches also ride the continuous-batching
verify service (crypto/verify_service.py): the depth-K burst issued by `_fill`
coalesces into shared kernel launches with whatever else is verifying
concurrently (the consensus drain, light range chunks, other fabric
nodes), and the service's executor owns the batched readback — `prefetch`
below then simply waits on the already-coalesced results instead of
issuing its own fetch.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.validator_set import PendingCommitVerify
from tendermint_tpu.utils import trace as _trace

DEFAULT_DEPTH = 4


def verify_ahead_depth() -> int:
    """How many blocks' commit verifications may be in flight while earlier
    blocks save/apply. TM_TPU_VERIFY_AHEAD overrides; read per call so tests
    and operators can flip it without restarting the sync."""
    v = os.environ.get("TM_TPU_VERIFY_AHEAD")
    if not v:
        return DEFAULT_DEPTH
    try:
        return max(1, int(v))
    except ValueError:
        return DEFAULT_DEPTH


@dataclass
class _Entry:
    """One speculative decision: block `first` at `height`, verified by
    `second`'s LastCommit, dispatched against the validator set whose hash
    was `vals_hash`."""

    height: int
    first: object
    second: object
    first_parts: object
    first_id: BlockID
    pending: PendingCommitVerify
    vals_hash: bytes


class VerifyAheadPipeline:
    """Bounded depth-K speculative commit-verify queue over a BlockPool.

    The reactor surface it drives (shared by v0 and v1): `.pool`, `.state`
    (read AND reassigned after apply), `.block_store`, `.block_exec`, and
    `._punish_invalid(height, exc)` implementing the reactor's existing
    invalid-block path (redo h and h+1, punish both senders)."""

    def __init__(self) -> None:
        self._entries: deque[_Entry] = deque()

    def __len__(self) -> int:
        return len(self._entries)

    def discard(self) -> None:
        """Drop all speculative in-flight work (failed resolve, stale
        inputs). Already-issued device work is simply never fetched."""
        self._entries.clear()

    # --- dispatch ----------------------------------------------------------

    def _force_device(self, reactor) -> bool:
        """Pin speculative dispatches to the device kernel when pipelining
        on a real accelerator. The calibrated host crossover
        (ops/ed25519_batch.host_crossover) prices a FULL sync floor into
        every flush — right for one synchronous decision, wrong here: the
        pipeline's whole point is hiding that floor behind K decisions of
        host work (copy_to_host_async starts the D2H at dispatch), after
        which the kernel's marginal us/sig beats the host C verifier for
        any kernel-sized batch. On a CPU backend the "device" is this same
        host — no tunnel to hide, kernel never pays off — and small
        commits (tests, dev nets) stay on the adaptive host/scalar path."""
        depth = verify_ahead_depth()
        if depth <= 1 or os.environ.get("TM_TPU_DISABLE_BATCH") == "1":
            return False
        try:
            import jax

            from tendermint_tpu.ops import ed25519_batch
        except Exception:  # noqa: BLE001 - no jax, no kernels to pin
            return False
        if jax.default_backend() == "cpu":
            return False
        est_per = (2 * reactor.state.validators.size()) // 3 + 1
        return est_per >= ed25519_batch.MIN_BUCKET

    def _dispatch_entry(self, reactor, height: int) -> _Entry | None:
        pool = reactor.pool
        first = pool.peek_block(height)
        second = pool.peek_block(height + 1)
        if first is None or second is None:
            return None
        state = reactor.state
        first_parts = PartSet.from_data(first.marshal())
        first_id = BlockID(hash=first.hash(), part_set_header=first_parts.header())
        try:
            # same pre-checks, in the same order, as the serial loop
            if second.last_commit is None:
                raise ValueError("second block has no LastCommit")
            if second.last_commit.block_id != first_id:
                raise ValueError("second block's LastCommit is for a different block")
            tr = _trace.current()
            if tr.enabled:
                # the dispatch span's height is inherited by the crypto
                # layer's host_prep/queue/readback phases (utils/trace.py)
                with tr.span("fastsync.dispatch", height=height):
                    pending = state.validators.verify_commit_light_async(
                        state.chain_id, first_id, first.header.height,
                        second.last_commit,
                        force_device=self._force_device(reactor))
            else:
                pending = state.validators.verify_commit_light_async(
                    state.chain_id, first_id, first.header.height,
                    second.last_commit,
                    force_device=self._force_device(reactor))
        except Exception as e:  # noqa: BLE001 - decided at resolve time, in order
            pending = PendingCommitVerify(error=e)
        return _Entry(height=height, first=first, second=second,
                      first_parts=first_parts, first_id=first_id,
                      pending=pending, vals_hash=state.validators.hash())

    def _fill(self, reactor) -> None:
        depth = verify_ahead_depth()
        pool = reactor.pool
        want = pool.height + len(self._entries)
        while len(self._entries) < depth:
            e = self._dispatch_entry(reactor, want)
            if e is None:
                return
            self._entries.append(e)
            want += 1

    # --- the one step both reactors call -----------------------------------

    def process_next(self, reactor) -> bool:
        """Verify + apply the next contiguous block through the pipeline.
        Returns True when a block was applied (call again to drain), False
        when the next block isn't ready or its commit was invalid (peers
        already punished, exactly as the serial path)."""
        tracer = getattr(reactor, "tracer", None)
        if tracer is not None and tracer.enabled:
            # spans from this step (speculative dispatches, the batched
            # readback, the apply) land in the syncing node's recorder
            with tracer.activate():
                return self._process_next(reactor)
        return self._process_next(reactor)

    def _process_next(self, reactor) -> bool:
        pool = reactor.pool
        for _ in range(2):
            self._fill(reactor)
            if not self._entries:
                return False
            head = self._entries[0]
            # Re-validate dispatch-time inputs against current reality; the
            # serial loop peeks at process time, so stale speculation must
            # be re-dispatched, never resolved.
            first, second = pool.peek_two_blocks()
            if (head.height != pool.height
                    or first is not head.first or second is not head.second
                    or head.vals_hash != reactor.state.validators.hash()):
                self.discard()
                continue
            break
        else:
            return False

        # Batch the readbacks of every in-flight decision into ONE
        # device_get: K floors -> 1. Entries already resolved (or
        # host-resolved) are untouched; later resolves are then instant.
        head = self._entries.popleft()
        try:
            if head.pending.pending is not None and head.pending.pending.has_device_output():
                crypto_batch.prefetch(
                    [e.pending.pending for e in [head, *self._entries]
                     if e.pending.pending is not None])
            head.pending.resolve()
        except Exception as e:  # noqa: BLE001 - the serial invalid-block path
            self.discard()
            reactor._punish_invalid(head.height, e)
            return False
        pool.pop_request()
        # Commit→apply overlap (docs/EXECUTION.md), both directions:
        # (a) with h popped, h+1 is the new pool head — top the
        #     speculative window up NOW so h+1's commit verification is
        #     in flight on-device while h saves/applies below (validator
        #     churn in this apply is caught by the next iteration's
        #     stale-input check and re-dispatched);
        # (b) dispatch h's own LastCommit re-verification (apply_block's
        #     internal validate) so it rides under the block-store save.
        self._fill(reactor)
        # duck-typed executors (headless replay / test stubs) don't
        # speculate and keep their plain apply_block signature
        dispatch = getattr(reactor.block_exec, "dispatch_commit_verify", None)
        commit_pending = dispatch(reactor.state, head.first) if dispatch else None
        with _trace.current().span("fastsync.apply", height=head.height):
            reactor.block_store.save_block(head.first, head.first_parts,
                                           head.second.last_commit)
            if dispatch is not None:
                reactor.state, _ = reactor.block_exec.apply_block(
                    reactor.state, head.first_id, head.first,
                    commit_pending=commit_pending)
            else:
                reactor.state, _ = reactor.block_exec.apply_block(
                    reactor.state, head.first_id, head.first)
        return True
