"""Fast sync v2: routine-based scheduler/processor (reference:
blockchain/v2/scheduler.go, processor.go, routine.go, reactor.go).

Same wire protocol + verification as v0/v1; the v2 architecture splits the
work into two independent routines connected by event queues:

  scheduler  -- owns peer state + block request planning (which height from
                which peer, in-flight tracking, timeouts, peer scoring)
  processor  -- owns verification + application of contiguous blocks
                (VerifyCommitLight per block, the batched kernel call)

The demuxer (the reactor) routes wire messages to the scheduler, scheduler
decisions to the network, fetched blocks to the processor, and processor
verdicts back to the scheduler. Selected with config.fastsync.version="v2".
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.blockchain.reactor import (
    BLOCKCHAIN_CHANNEL,
    msg_block_request,
    msg_block_response,
    msg_no_block_response,
    msg_status_request,
    msg_status_response,
)
from tendermint_tpu.encoding import proto
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSet

REQUEST_TIMEOUT_S = 10.0
MAX_IN_FLIGHT_PER_PEER = 8


# --- events (reference: blockchain/v2/events.go + scheduler.go) -------------


@dataclass
class EvAddPeer:
    peer_id: str


@dataclass
class EvRemovePeer:
    peer_id: str


@dataclass
class EvStatus:
    peer_id: str
    base: int
    height: int


@dataclass
class EvBlockResponse:
    peer_id: str
    block: Block


@dataclass
class EvNoBlock:
    peer_id: str
    height: int


@dataclass
class EvBlockProcessed:
    height: int


@dataclass
class EvBlockInvalid:
    height: int
    peer_id: str


@dataclass
class EvTick:
    pass


class Scheduler:
    """Pure planning state machine (reference: scheduler.go:136 scheduler).

    handle(event) -> list of actions: ("request", peer_id, height) |
    ("drop_peer", peer_id, reason) | ("finished",)."""

    def __init__(self, initial_height: int):
        self.height = initial_height  # next height to schedule/process
        self.peers: dict[str, tuple[int, int]] = {}  # id -> (base, top)
        self.pending: dict[int, tuple[str, float]] = {}  # height -> (peer, at)
        self.received: set[int] = set()

    def max_peer_height(self) -> int:
        return max((t for _, t in self.peers.values()), default=0)

    def handle(self, ev) -> list[tuple]:
        acts: list[tuple] = []
        if isinstance(ev, EvStatus):
            self.peers[ev.peer_id] = (ev.base, ev.height)
        elif isinstance(ev, (EvAddPeer,)):
            pass  # peer becomes schedulable once its status arrives
        elif isinstance(ev, EvRemovePeer):
            self.peers.pop(ev.peer_id, None)
            for h in [h for h, (p, _) in self.pending.items() if p == ev.peer_id]:
                del self.pending[h]
        elif isinstance(ev, EvBlockResponse):
            h = ev.block.header.height
            if self.solicited(ev.peer_id, h):
                self.pending.pop(h, None)
                self.received.add(h)
            # else: unsolicited -- IGNORED, not punished. The reference
            # scheduler validates responses against pendingBlocks
            # (blockchain/v2/scheduler.go handleBlockResponse) so a peer
            # cannot clear others' pending slots or pin arbitrary data; we
            # don't drop the sender because a timeout reassignment makes a
            # late HONEST response indistinguishable from a malicious one.
        elif isinstance(ev, EvNoBlock):
            if self.solicited(ev.peer_id, ev.height):
                self.pending.pop(ev.height, None)
                acts.append(("drop_peer", ev.peer_id,
                             "no block for advertised height"))
            # else: stale/unsolicited NoBlock -- same reasoning as above.
        elif isinstance(ev, EvBlockProcessed):
            self.height = ev.height + 1
            self.received.discard(ev.height)
            if self.caught_up():
                acts.append(("finished",))
                return acts
        elif isinstance(ev, EvBlockInvalid):
            # everything from that peer is suspect; re-schedule
            acts.append(("drop_peer", ev.peer_id, "invalid block"))
            self.received.discard(ev.height)
        elif isinstance(ev, EvTick):
            now = time.monotonic()
            timed_out: set[str] = set()
            for h, (p, at) in list(self.pending.items()):
                if now - at > REQUEST_TIMEOUT_S:
                    del self.pending[h]
                    timed_out.add(p)
            # Drop the timed-out peer entirely (reference scheduler
            # peer-timeout semantics): silently reassigning its heights
            # would make its late honest response look unsolicited.
            for p in timed_out:
                acts.append(("drop_peer", p, "block request timeout"))
            if self.caught_up():
                acts.append(("finished",))
                return acts
        acts.extend(self._schedule())
        return acts

    def solicited(self, peer_id: str, height: int) -> bool:
        """True iff `height` is pending from exactly this peer."""
        pend = self.pending.get(height)
        return pend is not None and pend[0] == peer_id

    def forget(self, heights) -> None:
        """Purged buffered blocks must leave `received` too, or _schedule
        skips their heights forever and sync deadlocks."""
        for h in heights:
            self.received.discard(h)
            self.pending.pop(h, None)

    def caught_up(self) -> bool:
        """v0 semantics (pool.is_caught_up): next height to sync has reached
        the best peer's tip -- the tip block itself commits via consensus."""
        return bool(self.peers) and self.height >= self.max_peer_height()

    def _schedule(self) -> list[tuple]:
        """Plan new requests (reference: scheduler.go trySchedule)."""
        acts = []
        in_flight: dict[str, int] = {}
        for p, _ in self.pending.values():
            in_flight[p] = in_flight.get(p, 0) + 1
        for h in range(self.height, self.height + 32):
            if h in self.pending or h in self.received:
                continue
            candidates = [p for p, (b, t) in self.peers.items()
                          if b <= h <= t and in_flight.get(p, 0) < MAX_IN_FLIGHT_PER_PEER]
            if not candidates:
                continue
            peer = candidates[h % len(candidates)]
            in_flight[peer] = in_flight.get(peer, 0) + 1
            self.pending[h] = (peer, time.monotonic())
            acts.append(("request", peer, h))
        return acts


class Processor:
    """Verify + apply contiguous blocks (reference: processor.go:38
    pcState). Owns the block buffer; emits processed/invalid events."""

    def __init__(self, state, block_exec, block_store):
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.blocks: dict[int, tuple[Block, str]] = {}

    def add(self, block: Block, peer_id: str) -> None:
        self.blocks[block.header.height] = (block, peer_id)

    def purge_peer(self, peer_id: str) -> list[int]:
        """Drop this peer's buffered blocks; returns the purged heights so
        the scheduler can forget them (received-set hygiene)."""
        hs = [h for h, (_, p) in self.blocks.items() if p == peer_id]
        for h in hs:
            del self.blocks[h]
        return hs

    def try_process(self, height: int) -> list:
        """Process as many contiguous (first, second) pairs as available
        (reference: processor.go handleProcessBlock)."""
        events = []
        while True:
            first = self.blocks.get(height)
            second = self.blocks.get(height + 1)
            if first is None or second is None:
                return events
            block, peer_id = first
            first_parts = PartSet.from_data(block.marshal())
            first_id = BlockID(hash=block.hash(),
                               part_set_header=first_parts.header())
            try:
                sec = second[0]
                if sec.last_commit is None:
                    raise ValueError("second block has no LastCommit")
                if sec.last_commit.block_id != first_id:
                    raise ValueError("second block's LastCommit mismatch")
                self.state.validators.verify_commit_light(
                    self.state.chain_id, first_id, block.header.height,
                    sec.last_commit)
            except Exception:  # noqa: BLE001
                # The invalid LastCommit is carried by the SECOND block, so
                # both peers are suspect: purge both blocks and punish both
                # (reference: blockchain/v2/processor.go:170-176). An event
                # is emitted for EACH height so the scheduler forgets both
                # from `received` even when one peer served both blocks.
                second_peer = second[1]
                self.blocks.pop(height, None)
                self.blocks.pop(height + 1, None)
                events.append(EvBlockInvalid(height, peer_id))
                events.append(EvBlockInvalid(height + 1, second_peer))
                return events
            del self.blocks[height]
            self.block_store.save_block(block, first_parts, sec.last_commit)
            self.state, _ = self.block_exec.apply_block(self.state, first_id, block)
            events.append(EvBlockProcessed(height))
            height += 1


class BlockchainReactorV2(Reactor):
    """The demuxer (reference: blockchain/v2/reactor.go)."""

    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None, logger=None):
        super().__init__("BLOCKCHAIN")
        self.state = state
        self.initial_state = state
        self.fast_sync = fast_sync
        self.block_store = block_store
        self.consensus_reactor = consensus_reactor
        self.logger = logger
        self.scheduler = Scheduler(block_store.height + 1)
        self.processor = Processor(state, block_exec, block_store)
        self.repairer = None  # the node's StoreRepairer (store/repair.py)
        self._events: queue.Queue = queue.Queue(maxsize=2000)
        self._running = False
        self._thread: threading.Thread | None = None
        self._synced = threading.Event()
        self._started_at = 0.0
        self._last_status_bcast = 0.0

    # expose pool-compat surface used by tests/tools
    @property
    def pool(self):
        return self.scheduler

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=10,
                                  recv_message_capacity=50 * 1024 * 1024)]

    def add_peer(self, peer: Peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL,
                      msg_status_response(self.block_store.height,
                                          self.block_store.base))
        peer.try_send(BLOCKCHAIN_CHANNEL, msg_status_request())
        self._post(EvAddPeer(peer.id))

    def remove_peer(self, peer: Peer, reason) -> None:
        self._post(EvRemovePeer(peer.id))

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 in f:  # BlockRequest: serving side
            m = proto.fields(f[1][-1])
            height = proto.as_sint64(m.get(1, [0])[-1])
            try:
                block = self.block_store.load_block(height)
            except CorruptedStoreError:
                block = None  # quarantined + scheduled; never serve rot
            if block is not None:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_block_response(block))
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_no_block_response(height))
        elif 2 in f:
            m = proto.fields(f[2][-1])
            self._post(EvNoBlock(peer.id, proto.as_sint64(m.get(1, [0])[-1])))
        elif 3 in f:
            m = proto.fields(f[3][-1])
            block = Block.unmarshal(m.get(1, [b""])[-1])
            rep = self.repairer
            if rep is not None:
                rep.offer_block(peer.id, block)
            self._post(EvBlockResponse(peer.id, block))
        elif 4 in f:
            peer.try_send(BLOCKCHAIN_CHANNEL,
                          msg_status_response(self.block_store.height,
                                              self.block_store.base))
        elif 5 in f:
            m = proto.fields(f[5][-1])
            self._post(EvStatus(peer.id,
                                proto.as_sint64(m.get(2, [0])[-1]),
                                proto.as_sint64(m.get(1, [0])[-1])))

    def _post(self, ev) -> None:
        try:
            self._events.put_nowait(ev)
        except queue.Full:
            pass

    # --- lifecycle ----------------------------------------------------------

    def start_sync(self) -> None:
        self._running = True
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._demux, name="fastsync-v2",
                                        daemon=True)
        self._thread.start()

    def switch_to_fast_sync(self, state) -> None:
        self.state = state
        self.initial_state = state
        self.processor.state = state
        self.scheduler.height = state.last_block_height + 1
        self.fast_sync = True
        self.start_sync()

    def on_stop(self) -> None:
        self._running = False

    def wait_until_synced(self, timeout: float) -> bool:
        return self._synced.wait(timeout)

    def expects_peers(self) -> bool:
        sw = self.switch
        return bool(sw is not None and (sw.peers or sw._persistent_addrs))

    # --- the demux routine (reference: reactor.go demux) --------------------

    def _demux(self) -> None:
        while self._running:
            now = time.monotonic()
            if self.switch is not None and now - self._last_status_bcast > 10.0:
                self.switch.broadcast(BLOCKCHAIN_CHANNEL, msg_status_request())
                self._last_status_bcast = now
            if (not self.scheduler.peers
                    and now - self._started_at > 15.0
                    and not self.expects_peers()):
                self._finish()  # solo node: nothing to sync from
                return
            try:
                ev = self._events.get(timeout=0.05)
            except queue.Empty:
                ev = EvTick()
            try:
                self._route(ev)
            except Exception as e:  # noqa: BLE001
                if self.logger:
                    self.logger.error("fastsync v2 event failed", err=e)
            if self._synced.is_set():
                return

    def _route(self, ev) -> None:
        if isinstance(ev, EvBlockResponse):
            if self.scheduler.solicited(ev.peer_id, ev.block.header.height):
                self.processor.add(ev.block, ev.peer_id)
        if isinstance(ev, EvRemovePeer):
            self.scheduler.forget(self.processor.purge_peer(ev.peer_id))
        for act in self.scheduler.handle(ev):
            self._apply_action(act)
        if isinstance(ev, (EvBlockResponse, EvTick)):
            for out in self.processor.try_process(self.scheduler.height):
                self.state = self.processor.state
                for act in self.scheduler.handle(out):
                    self._apply_action(act)

    def _apply_action(self, act: tuple) -> None:
        kind = act[0]
        if kind == "request":
            _, peer_id, height = act
            if self.switch is not None:
                with self.switch._peers_mtx:
                    p = self.switch.peers.get(peer_id)
                if p is not None:
                    p.try_send(BLOCKCHAIN_CHANNEL, msg_block_request(height))
        elif kind == "drop_peer":
            _, peer_id, reason = act
            self.scheduler.forget(self.processor.purge_peer(peer_id))
            if self.switch is not None:
                self.switch.stop_peer_by_id(peer_id, reason)
            self.scheduler.handle(EvRemovePeer(peer_id))
        elif kind == "finished":
            self._finish()

    def _finish(self) -> None:
        self._running = False
        self._synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(self.state)
