"""Fast sync v1: event-driven FSM (reference: blockchain/v1/reactor_fsm.go,
blockchain/v1/reactor.go).

Same wire protocol and verification as v0 (channel 0x40, VerifyCommitLight
per block -- one batched kernel call); the difference is structure: instead
of a polling loop, all input (peer status, block responses, peer removal,
scheduling ticks) becomes EVENTS consumed by a single FSM routine with
explicit states:

    unknown -> wait_for_peer -> wait_for_block -> finished

Selected with config.fastsync.version = "v1".
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass

from tendermint_tpu.blockchain.pipeline import VerifyAheadPipeline
from tendermint_tpu.blockchain.reactor import (
    BLOCKCHAIN_CHANNEL,
    BlockPool,
    msg_block_request,
    msg_block_response,
    msg_no_block_response,
    msg_status_request,
    msg_status_response,
)
from tendermint_tpu.encoding import proto
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.block import Block

# states (reference: reactor_fsm.go:22-28)
S_UNKNOWN = "unknown"
S_WAIT_FOR_PEER = "wait_for_peer"
S_WAIT_FOR_BLOCK = "wait_for_block"
S_FINISHED = "finished"

NO_PEER_TIMEOUT_S = 15.0  # reference: waitForPeerTimeout


@dataclass
class Ev:
    """FSM event (reference: reactor_fsm.go bcReactorEvent)."""

    kind: str  # start | status | block | no_block | remove_peer | tick | stop
    peer_id: str = ""
    base: int = 0
    height: int = 0
    block: Block | None = None


class FastSyncFSM:
    """reference: reactor_fsm.go:118 bcReactorFSM."""

    def __init__(self, reactor: "BlockchainReactorV1"):
        self.r = reactor
        self.state = S_UNKNOWN
        self.started_at = 0.0

    def handle(self, ev: Ev) -> None:
        if self.state == S_FINISHED:
            return
        if ev.kind == "start":
            self.started_at = time.monotonic()
            self._to(S_WAIT_FOR_PEER)
        elif ev.kind == "status":
            self.r.pool.set_peer_range(ev.peer_id, ev.base, ev.height)
            if self.state == S_WAIT_FOR_PEER:
                self._to(S_WAIT_FOR_BLOCK)
            self.r.make_requests()
        elif ev.kind == "block":
            if self.state != S_WAIT_FOR_BLOCK:
                return
            self.r.pool.add_block(ev.peer_id, ev.block)
            self._process_ready()
        elif ev.kind == "no_block":
            # peer advertised a height it can't serve: drop it — but only
            # when the POOL solicited that height from that peer. The store
            # repairer broadcasts BlockRequests outside the FSM, and an
            # honest peer answering NoBlock to one of those (pruned below
            # the height, still syncing) must not be torn down.
            if self.r.pool.solicited(ev.peer_id, ev.height):
                self.r.drop_peer(ev.peer_id, "no block for advertised height")
        elif ev.kind == "remove_peer":
            self.r.pool.remove_peer(ev.peer_id)
            if not self.r.pool.peers and self.state == S_WAIT_FOR_BLOCK:
                self._to(S_WAIT_FOR_PEER)
        elif ev.kind == "tick":
            if (self.state == S_WAIT_FOR_PEER
                    and time.monotonic() - self.started_at > NO_PEER_TIMEOUT_S
                    and not self.r.expects_peers()):
                self._finish()  # solo node: nothing to sync from
                return
            if self.state == S_WAIT_FOR_BLOCK:
                self._process_ready()
                if self.r.pool.is_caught_up():
                    self._finish()
                    return
            self.r.make_requests()

    def _process_ready(self) -> None:
        """Apply every contiguously-available verified block (reference:
        processBlock event handling)."""
        while True:
            if not self.r.try_process_block():
                return
            if self.r.pool.is_caught_up():
                self._finish()
                return

    def _to(self, state: str) -> None:
        self.state = state

    def _finish(self) -> None:
        self.state = S_FINISHED
        self.r.on_finished()


class BlockchainReactorV1(Reactor):
    """reference: blockchain/v1/reactor.go."""

    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None, logger=None):
        super().__init__("BLOCKCHAIN")
        self.state = state
        self.initial_state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.logger = logger
        self.pool = BlockPool(block_store.height + 1)
        self._pipeline = VerifyAheadPipeline()
        self.repairer = None  # the node's StoreRepairer (store/repair.py)
        self.fsm = FastSyncFSM(self)
        self._events: queue.Queue = queue.Queue(maxsize=1000)
        self._running = False
        self._thread: threading.Thread | None = None
        self._synced = threading.Event()
        self._last_status_bcast = 0.0

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=10,
                                  recv_message_capacity=50 * 1024 * 1024)]

    # --- peer lifecycle ------------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL,
                      msg_status_response(self.block_store.height, self.block_store.base))
        peer.try_send(BLOCKCHAIN_CHANNEL, msg_status_request())

    def remove_peer(self, peer: Peer, reason) -> None:
        self._post(Ev("remove_peer", peer_id=peer.id))

    def drop_peer(self, peer_id: str, reason: str) -> None:
        if self.switch is not None:
            self.switch.stop_peer_by_id(peer_id, reason)
        self._post(Ev("remove_peer", peer_id=peer_id))

    def expects_peers(self) -> bool:
        sw = self.switch
        return bool(sw is not None and (sw.peers or sw._persistent_addrs))

    # --- receive: wire messages -> events ------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 in f:  # BlockRequest (serving side, no FSM involvement)
            m = proto.fields(f[1][-1])
            height = proto.as_sint64(m.get(1, [0])[-1])
            try:
                block = self.block_store.load_block(height)
            except CorruptedStoreError:
                # quarantined + scheduled by the store's repair hook; never
                # serve rot, never kill the receive path (docs/DURABILITY.md)
                block = None
            if block is not None:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_block_response(block))
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_no_block_response(height))
        elif 2 in f:  # NoBlockResponse
            m = proto.fields(f[2][-1])
            self._post(Ev("no_block", peer_id=peer.id,
                          height=proto.as_sint64(m.get(1, [0])[-1])))
        elif 3 in f:  # BlockResponse
            m = proto.fields(f[3][-1])
            block = Block.unmarshal(m.get(1, [b""])[-1])
            rep = self.repairer
            if rep is not None:
                rep.offer_block(peer.id, block)
            self._post(Ev("block", peer_id=peer.id, block=block))
        elif 4 in f:  # StatusRequest
            peer.try_send(BLOCKCHAIN_CHANNEL,
                          msg_status_response(self.block_store.height, self.block_store.base))
        elif 5 in f:  # StatusResponse
            m = proto.fields(f[5][-1])
            self._post(Ev("status", peer_id=peer.id,
                          base=proto.as_sint64(m.get(2, [0])[-1]),
                          height=proto.as_sint64(m.get(1, [0])[-1])))

    def _post(self, ev: Ev) -> None:
        try:
            self._events.put_nowait(ev)
        except queue.Full:
            pass  # backpressure: ticks will recover state

    # --- FSM routine ----------------------------------------------------------

    def start_sync(self) -> None:
        self._running = True
        self._post(Ev("start"))
        self._thread = threading.Thread(target=self._routine,
                                        name="fastsync-v1", daemon=True)
        self._thread.start()

    def switch_to_fast_sync(self, state) -> None:
        """Re-enter fast sync (same surface as v0): the post-state-sync
        hand-off and the stall watchdog's hand-back both land here, so the
        FSM restarts from scratch with stale speculation discarded."""
        if self._running:
            return
        self.state = state
        self.initial_state = state
        self.pool.reset(state.last_block_height + 1)
        self._pipeline.discard()
        self._synced.clear()
        self.fsm.state = S_UNKNOWN
        self.fast_sync = True
        self.start_sync()

    def on_stop(self) -> None:
        self._running = False
        self._post(Ev("stop"))

    def wait_until_synced(self, timeout: float) -> bool:
        return self._synced.wait(timeout)

    def _routine(self) -> None:
        while self._running and self.fsm.state != S_FINISHED:
            now = time.monotonic()
            if self.switch is not None and now - self._last_status_bcast > 10.0:
                self.switch.broadcast(BLOCKCHAIN_CHANNEL, msg_status_request())
                self._last_status_bcast = now
            try:
                ev = self._events.get(timeout=0.05)
            except queue.Empty:
                ev = Ev("tick")
            if ev.kind == "stop":
                return
            try:
                self.fsm.handle(ev)
            except Exception as e:  # noqa: BLE001 - FSM must survive bad input
                if self.logger:
                    self.logger.error("fastsync v1 event failed", err=e)

    # --- actions used by the FSM ---------------------------------------------

    def make_requests(self) -> None:
        if self.switch is None:
            return
        with self.switch._peers_mtx:
            peers = dict(self.switch.peers)
        for h, pid in self.pool.wanted_requests():
            p = peers.get(pid)
            if p is not None:
                p.try_send(BLOCKCHAIN_CHANNEL, msg_block_request(h))

    def try_process_block(self) -> bool:
        """Verify + apply the next contiguous block through the depth-K
        verify-ahead pipeline (blockchain/pipeline.py); False when not ready
        (reference: processBlock -> VerifyCommitLight at reactor.go:478)."""
        return self._pipeline.process_next(self)

    def _punish_invalid(self, height: int, e: Exception) -> None:
        """The invalid LastCommit rides in the SECOND block: punish both
        senders (reference: blockchain/v1/reactor.go processBlock failure
        path redoes first.Height and first.Height+1)."""
        bad = self.pool.redo_request(height)
        bad2 = self.pool.redo_request(height + 1)
        board = getattr(self.switch, "scoreboard", None)
        for pid in {bad, bad2} - {None}:
            if board is not None:
                board.record(pid, "bad_message")  # escalates on redial loops
            self.drop_peer(pid, f"invalid block: {e}")

    def on_finished(self) -> None:
        self._running = False
        self._synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(self.state)
