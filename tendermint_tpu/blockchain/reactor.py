"""Fast sync: block pool + sync loop (reference: blockchain/v0/pool.go,
blockchain/v0/reactor.go:309-419; channel 0x40;
proto/tendermint/blockchain/types.proto).

The hot loop verifies each fetched block with the NEXT block's LastCommit
via VerifyCommitLight (reference: reactor.go:366) - on TPU one batched
kernel call per block, pipelined ACROSS blocks by the depth-K verify-ahead
queue (blockchain/pipeline.py, TM_TPU_VERIFY_AHEAD) so the device sync
floor amortizes over K decisions instead of gating each one.

Messages: BlockRequest=1{height}, NoBlockResponse=2{height},
BlockResponse=3{block}, StatusRequest=4{}, StatusResponse=5{height, base}.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.blockchain.pipeline import VerifyAheadPipeline
from tendermint_tpu.encoding import proto
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.block import Block

BLOCKCHAIN_CHANNEL = 0x40
TRY_SYNC_INTERVAL_S = 0.01
STATUS_UPDATE_INTERVAL_S = 10.0
SWITCH_TO_CONSENSUS_INTERVAL_S = 1.0
REQUEST_WINDOW = 16


def msg_block_request(height: int) -> bytes:
    return proto.Writer().message(1, proto.Writer().varint(1, height).out(), always=True).out()


def msg_no_block_response(height: int) -> bytes:
    return proto.Writer().message(2, proto.Writer().varint(1, height).out(), always=True).out()


def msg_block_response(block: Block) -> bytes:
    inner = proto.Writer().message(1, block.marshal(), always=True).out()
    return proto.Writer().message(3, inner, always=True).out()


def msg_status_request() -> bytes:
    return proto.Writer().message(4, b"", always=True).out()


def msg_status_response(height: int, base: int) -> bytes:
    return proto.Writer().message(
        5, proto.Writer().varint(1, height).varint(2, base).out(), always=True
    ).out()


class BlockPool:
    """reference: blockchain/v0/pool.go."""

    def __init__(self, start_height: int):
        self.height = start_height  # next height to sync
        self.peers: dict[str, tuple[int, int]] = {}  # id -> (base, height)
        self.blocks: dict[int, tuple[Block, str]] = {}  # height -> (block, peer)
        self.requested: dict[int, str] = {}
        self._mtx = threading.RLock()

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        with self._mtx:
            self.peers[peer_id] = (base, height)

    def reset(self, start_height: int) -> None:
        """Re-arm the pool for a fresh sync round (the watchdog hand-back):
        forget peer ranges and buffered blocks. Ranges recorded before a
        partition sit at ≈ our own stalled height, so keeping them would
        fake an instant is_caught_up() and bounce the node straight back
        into stalled consensus; fresh StatusResponses repopulate them
        within one status broadcast."""
        with self._mtx:
            self.height = start_height
            self.peers = {}
            self.blocks = {}
            self.requested = {}

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self.peers.pop(peer_id, None)
            for h in [h for h, p in self.requested.items() if p == peer_id]:
                del self.requested[h]
            for h in [h for h, (_, p) in self.blocks.items() if p == peer_id]:
                del self.blocks[h]

    def max_peer_height(self) -> int:
        with self._mtx:
            return max((h for _, h in self.peers.values()), default=0)

    def is_caught_up(self) -> bool:
        with self._mtx:
            if not self.peers:
                return False
            return self.height >= self.max_peer_height()

    def add_block(self, peer_id: str, block: Block) -> None:
        with self._mtx:
            h = block.header.height
            if h < self.height or h in self.blocks:
                return
            self.blocks[h] = (block, peer_id)
            self.requested.pop(h, None)

    def peek_two_blocks(self) -> tuple[Block | None, Block | None]:
        with self._mtx:
            first = self.blocks.get(self.height, (None, None))[0]
            second = self.blocks.get(self.height + 1, (None, None))[0]
            return first, second

    def peek_block(self, height: int) -> Block | None:
        """Peek any pooled height without popping (the verify-ahead
        pipeline speculates past self.height)."""
        with self._mtx:
            return self.blocks.get(height, (None, None))[0]

    def pop_request(self) -> None:
        with self._mtx:
            self.blocks.pop(self.height, None)
            self.height += 1

    def redo_request(self, height: int) -> str | None:
        """Invalid block: drop it + the peer that sent it."""
        with self._mtx:
            bad_peer = None
            if height in self.blocks:
                bad_peer = self.blocks[height][1]
            for h in [h for h, (_, p) in self.blocks.items() if p == bad_peer]:
                del self.blocks[h]
            return bad_peer

    def solicited(self, peer_id: str, height: int) -> bool:
        """True when this pool has an outstanding request for ``height``
        addressed to ``peer_id`` (mirrors the v2 scheduler's guard: other
        actors — notably the store repairer — send BlockRequests of their
        own, and a peer's honest NoBlock answer to one of those must not
        be punished)."""
        with self._mtx:
            return self.requested.get(height) == peer_id

    def wanted_requests(self) -> list[tuple[int, str]]:
        """Pick heights to request and a peer for each."""
        with self._mtx:
            out = []
            for h in range(self.height, self.height + REQUEST_WINDOW):
                if h in self.blocks or h in self.requested:
                    continue
                candidates = [pid for pid, (b, ph) in self.peers.items()
                              if b <= h <= ph]
                if not candidates:
                    continue
                pid = candidates[h % len(candidates)]
                self.requested[h] = pid
                out.append((h, pid))
            return out


class BlockchainReactor(Reactor):
    def __init__(self, state, block_exec, block_store, fast_sync: bool,
                 consensus_reactor=None, logger=None):
        super().__init__("BLOCKCHAIN")
        self.initial_state = state
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.logger = logger
        self.pool = BlockPool(block_store.height + 1)
        self._pipeline = VerifyAheadPipeline()
        # the node's StoreRepairer (store/repair.py): BlockResponses feed
        # its fetch waiters, corrupt serving-side loads route to it
        self.repairer = None
        self._running = False
        self._thread: threading.Thread | None = None
        self._synced = threading.Event()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=10,
                                  recv_message_capacity=50 * 1024 * 1024)]

    # --- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL,
                      msg_status_response(self.block_store.height, self.block_store.base))
        peer.try_send(BLOCKCHAIN_CHANNEL, msg_status_request())

    def remove_peer(self, peer: Peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    # --- receive -----------------------------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if 1 in f:  # BlockRequest
            m = proto.fields(f[1][-1])
            height = proto.as_sint64(m.get(1, [0])[-1])
            try:
                block = self.block_store.load_block(height)
            except CorruptedStoreError:
                # thread-crash-surface rule: a rotten record must not kill
                # this receive path OR be served — the store's repair hook
                # has already quarantined + scheduled the height; answer
                # no-block so the peer retries elsewhere meanwhile
                block = None
            if block is not None:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_block_response(block))
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, msg_no_block_response(height))
        elif 3 in f:  # BlockResponse
            m = proto.fields(f[3][-1])
            block = Block.unmarshal(m.get(1, [b""])[-1])
            rep = self.repairer
            if rep is not None:
                rep.offer_block(peer.id, block)
            self.pool.add_block(peer.id, block)
        elif 4 in f:  # StatusRequest
            peer.try_send(BLOCKCHAIN_CHANNEL,
                          msg_status_response(self.block_store.height, self.block_store.base))
        elif 5 in f:  # StatusResponse
            m = proto.fields(f[5][-1])
            height = proto.as_sint64(m.get(1, [0])[-1])
            base = proto.as_sint64(m.get(2, [0])[-1])
            self.pool.set_peer_range(peer.id, base, height)

    # --- sync loop (reference: blockchain/v0/reactor.go:309-419) -----------

    def start_sync(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._pool_routine, daemon=True)
        self._thread.start()

    def switch_to_fast_sync(self, state) -> None:
        """Re-enter fast sync from the given state. Two callers: the
        state-sync bootstrap hand-off (reference: blockchain/v0/reactor.go
        :109 SwitchToFastSync, node.go:991 startStateSync), and the
        consensus stall watchdog handing a stalled node back for catchup —
        so this must be re-entrant: stale speculation is discarded and the
        synced latch re-arms."""
        if self._running:
            return
        self.state = state
        self.initial_state = state
        self.pool.reset(state.last_block_height + 1)
        self._pipeline.discard()
        self._synced.clear()
        self.fast_sync = True
        self.start_sync()

    def on_stop(self) -> None:
        self._running = False

    def wait_until_synced(self, timeout: float) -> bool:
        return self._synced.wait(timeout)

    def _pool_routine(self) -> None:
        try:
            self._pool_loop()
        except Exception as e:  # noqa: BLE001 - fail-stop, never die silent
            if self.logger is not None:
                self.logger.error("fast-sync pool routine crashed", err=e)
            self._running = False

    def _pool_loop(self) -> None:
        last_status = 0.0
        last_switch_check = 0.0
        started_at = time.monotonic()
        while self._running:
            now = time.monotonic()
            if now - last_status > STATUS_UPDATE_INTERVAL_S:
                if self.switch is not None:
                    self.switch.broadcast(BLOCKCHAIN_CHANNEL, msg_status_request())
                last_status = now
            # issue requests
            if self.switch is not None:
                with self.switch._peers_mtx:
                    peers = dict(self.switch.peers)
                for h, pid in self.pool.wanted_requests():
                    p = peers.get(pid)
                    if p is not None:
                        p.try_send(BLOCKCHAIN_CHANNEL, msg_block_request(h))
            # switch to consensus when caught up
            if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL_S:
                last_switch_check = now
                caught_up = self.pool.is_caught_up()
                # The no-peer bailout exists for solo/dev nodes; a node that
                # HAS peers configured (persistent peers or a PEX book that
                # can still produce some) must keep waiting instead of
                # silently skipping sync on a cold start.
                waited_enough = now - started_at > 3.0
                no_peers = self.switch is None or not self.switch.peers
                expects_peers = self.switch is not None and (
                    self.switch._persistent_addrs
                    or any(r.name == "PEX" and not r.book.is_empty()
                           for r in self.switch.reactors.values()
                           if hasattr(r, "book")))
                if caught_up or (waited_enough and no_peers and not expects_peers):
                    self._running = False
                    self._synced.set()
                    if self.consensus_reactor is not None:
                        self.consensus_reactor.switch_to_consensus(self.state)
                    return
            # Drain: process every contiguously-available block before
            # sleeping. The old one-block-per-tick pacing capped sync at
            # 1/TRY_SYNC_INTERVAL_S blocks/s however fast verification ran.
            while self._running and self._try_sync():
                pass
            time.sleep(TRY_SYNC_INTERVAL_S)

    def _try_sync(self) -> bool:
        """Verify + apply the next block through the depth-K verify-ahead
        pipeline (blockchain/pipeline.py): commit verification for blocks
        h..h+K-1 is dispatched while block h saves/applies, readbacks are
        batched, decisions resolve in height order with serial semantics
        (reference: reactor.go:366 VerifyCommitLight). True when a block
        was applied."""
        return self._pipeline.process_next(self)

    def _punish_invalid(self, height: int, e: Exception) -> None:
        """Punish BOTH senders: the bad LastCommit is carried by the
        second block (reference: blockchain/v0/reactor.go:394-408).
        Scored as well as disconnected (docs/OVERLOAD.md) — a fast-sync
        peer feeding invalid blocks in a redial loop must escalate to a
        ban, not recycle free disconnects."""
        bad = self.pool.redo_request(height)
        bad2 = self.pool.redo_request(height + 1)
        if self.switch is not None:
            board = getattr(self.switch, "scoreboard", None)
            for pid in {bad, bad2} - {None}:
                if board is not None:
                    board.record(pid, "bad_message")
                if pid in self.switch.peers:
                    self.switch.stop_peer_for_error(
                        self.switch.peers[pid], f"invalid block: {e}")
