"""BlockStore: persists blocks as meta + parts + commits (reference:
store/store.go:93,203,226,248,332).

Layout (one KV row per item, like the reference's calc*Key scheme):
  H:<height>        -> BlockMeta proto
  P:<height>:<idx>  -> Part proto
  C:<height>        -> Commit proto   (LastCommit of height+1)
  SC:<height>       -> Commit proto   (locally seen commit for height)
  BH:<hash>         -> height (decimal)
  blockStore        -> BlockStoreState {base, height}

Every value is written inside the CRC32 integrity envelope
(store/envelope.py) and every read routes through the checked decode: a
flipped bit raises a typed CorruptedStoreError naming the key (and fires
the ``on_corruption`` repair hook) instead of an unhandled proto error or
a silently-served bad block. Pre-envelope rows read compatibly
(docs/DURABILITY.md).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field

from tendermint_tpu.encoding import proto
from tendermint_tpu.store import envelope
from tendermint_tpu.store.db import DB, prefix_end
from tendermint_tpu.utils import faults
from tendermint_tpu.types.block import Block, Commit, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import Part, PartSet


@dataclass
class BlockMeta:
    """reference: types/block_meta.go."""

    block_id: BlockID = dc_field(default_factory=BlockID)
    block_size: int = 0
    header: Header = dc_field(default_factory=Header)
    num_txs: int = 0

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .message(1, self.block_id.marshal(), always=True)
            .varint(2, self.block_size)
            .message(3, self.header.marshal(), always=True)
            .varint(4, self.num_txs)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "BlockMeta":
        f = proto.fields(buf)
        return BlockMeta(
            block_id=BlockID.unmarshal(f.get(1, [b""])[-1]),
            block_size=proto.as_sint64(f.get(2, [0])[-1]),
            header=Header.unmarshal(f.get(3, [b""])[-1]),
            num_txs=proto.as_sint64(f.get(4, [0])[-1]),
        )


def _meta_key(h: int) -> bytes:
    return b"H:%020d" % h


def _part_key(h: int, i: int) -> bytes:
    return b"P:%020d:%08d" % (h, i)


def _commit_key(h: int) -> bytes:
    return b"C:%020d" % h


def _seen_commit_key(h: int) -> bytes:
    return b"SC:%020d" % h


def _hash_key(block_hash: bytes) -> bytes:
    return b"BH:" + block_hash


_STATE_KEY = b"blockStore"


def _block_rows(block: Block, part_set: PartSet) -> list:
    """The meta / BH / part / last-commit rows every block writer lays
    down. save_block and the repair path's rewrite_block share this so a
    repaired height is byte-identical to a freshly saved one — any layout
    change lands in both writers at once."""
    height = block.header.height
    block_id = BlockID(hash=block.hash(), part_set_header=part_set.header())
    meta = BlockMeta(
        block_id=block_id,
        block_size=sum(len(p.bytes_) for p in part_set.parts),
        header=block.header,
        num_txs=len(block.data.txs),
    )
    sets = [(_meta_key(height), envelope.wrap(meta.marshal())),
            (_hash_key(block.hash()), envelope.wrap(str(height).encode()))]
    for i, part in enumerate(part_set.parts):
        sets.append((_part_key(height, i), envelope.wrap(part.marshal())))
    if block.last_commit is not None:
        sets.append((_commit_key(height - 1),
                     envelope.wrap(block.last_commit.marshal())))
    return sets

LOAD_SITE = "store.block.load"


class BlockStore:
    """Thread-safe; mirrors store/store.go semantics including pruning."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        # repair hook: the node wires this to its StoreRepairer so every
        # detection quarantines + schedules without the caller's help
        self.on_corruption = None
        st = db.get(_STATE_KEY)
        if st is None:
            self.base = 0
            self.height = 0
        else:
            try:
                f = self._decode(_STATE_KEY, st, proto.fields)
                self.base = proto.as_sint64(f.get(1, [0])[-1])
                self.height = proto.as_sint64(f.get(2, [0])[-1])
            except envelope.CorruptedStoreError:
                # the {base, height} row is fully re-derivable from the H:
                # keyspace: self-heal instead of refusing to construct
                self.base, self.height = self._rederive_state()
                envelope.quarantine(db, envelope.CorruptedStoreError(
                    "block", _STATE_KEY, "rederived after corruption", st))
                db.set(_STATE_KEY, envelope.wrap(self._state_bytes()))
                envelope.count_repair("block")

    def _rederive_state(self) -> tuple[int, int]:
        lo = next(self._db.iterator(b"H:", prefix_end(b"H:")), None)
        hi = next(self._db.reverse_iterator(b"H:", prefix_end(b"H:")), None)
        if lo is None or hi is None:
            return 0, 0
        return int(lo[0][2:]), int(hi[0][2:])

    # --- the checked read path --------------------------------------------

    def _load(self, key: bytes, fn):
        """DB get -> fault site -> envelope unwrap -> guarded decode."""
        raw = faults.mutate_value(LOAD_SITE, self._db.get(key))
        if raw is None:
            return None
        return self._decode(key, raw, fn)

    def _decode(self, key: bytes, raw: bytes, fn):
        return envelope.decode(raw, "block", key, fn,
                               on_corruption=self.on_corruption)

    # --- accessors ---------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return 0 if self.height == 0 else self.height - self.base + 1

    def load_base_meta(self) -> BlockMeta | None:
        with self._mtx:
            base = self.base
        return self.load_block_meta(base) if base else None

    def load_block_meta(self, height: int) -> BlockMeta | None:
        return self._load(_meta_key(height), BlockMeta.unmarshal)

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            part = self._load(_part_key(height, i), Part.unmarshal)
            if part is None:
                return None
            parts.append(part.bytes_)
        # the joined payload is unframed; the guarded decode still converts
        # any unmarshal blow-up into the typed error naming the height
        return self._decode(_meta_key(height), b"".join(parts),
                            Block.unmarshal)

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        h = self._load(_hash_key(block_hash), envelope.decimal_height)
        if h is None:
            return None
        return self.load_block(h)

    def load_block_part(self, height: int, index: int) -> Part | None:
        return self._load(_part_key(height, index), Part.unmarshal)

    def load_block_commit(self, height: int) -> Commit | None:
        """Commit for `height` stored with block height+1 (reference:
        store/store.go:203)."""
        return self._load(_commit_key(height), Commit.unmarshal)

    def load_seen_commit(self, height: int) -> Commit | None:
        return self._load(_seen_commit_key(height), Commit.unmarshal)

    # --- mutation ----------------------------------------------------------

    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """reference: store/store.go:332-383."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        height = block.header.height
        with self._mtx:
            want = self.height + 1
            if self.height > 0 and height != want:
                raise ValueError(f"BlockStore can only save contiguous blocks. Wanted {want}, got {height}")
            if not part_set.is_complete():
                raise ValueError("BlockStore can only save complete block part sets")

            sets = _block_rows(block, part_set)
            sets.append((_seen_commit_key(height),
                         envelope.wrap(seen_commit.marshal())))

            self.height = height
            if self.base == 0:
                self.base = height
            sets.append((_STATE_KEY, envelope.wrap(self._state_bytes())))
            faults.fire("store.block.save")
            self._db.write_batch(sets)

    def save_seen_commit(self, height: int, seen_commit: Commit) -> None:
        """Standalone seen-commit write for the state-sync bootstrap
        (reference: store/store.go:385 SaveSeenCommit)."""
        with self._mtx:
            self._db.set(_seen_commit_key(height),
                         envelope.wrap(seen_commit.marshal()))

    def rewrite_block(self, block: Block, part_set: PartSet,
                      commit: Commit | None) -> bool:
        """Repair-path write: re-lay every row of an ALREADY-COMMITTED
        height from a verified block (store/repair.py), without the
        contiguity/state bookkeeping of save_block — base/height are
        untouched, the damage was record-level. Returns False without
        writing when the height left the live range while the repair was
        in flight (a concurrent prune_blocks advanced ``base``): rows
        re-laid below base would never be revisited by pruning and leak
        forever."""
        height = block.header.height
        sets = _block_rows(block, part_set)
        if commit is not None:
            # fill only the commit rows the damage took: an intact C: row
            # keeps its original bytes, a lost SC: row is restored from the
            # canonical commit (a different-but-valid +2/3 sig set is fine)
            if self._db.get(_commit_key(height)) is None:
                sets.append((_commit_key(height),
                             envelope.wrap(commit.marshal())))
            if self._db.get(_seen_commit_key(height)) is None:
                sets.append((_seen_commit_key(height),
                             envelope.wrap(commit.marshal())))
        with self._mtx:
            if not (self.base <= height <= self.height):
                return False  # pruned (or rolled back) mid-repair
            self._db.write_batch(sets)
        return True

    def prune_blocks(self, height: int) -> int:
        """Removes blocks below `height`, keeping `height` (reference:
        store/store.go:248-330). Returns number pruned."""
        with self._mtx:
            if height <= 0:
                raise ValueError("height must be greater than 0")
            if height > self.height:
                raise ValueError(f"cannot prune beyond the latest height {self.height}")
            if height < self.base:
                return 0
            pruned = 0
            deletes: list[bytes] = []
            bh_index = None  # built on first corrupt meta, shared by all
            for h in range(self.base, height):
                try:
                    meta = self.load_block_meta(h)
                except envelope.CorruptedStoreError:
                    # a corrupt meta must not wedge pruning OR leak its
                    # height's rows forever: fall back to prefix scans (one
                    # BH: keyspace pass per prune call, not per height —
                    # this all runs under the store mutex)
                    if bh_index is None:
                        bh_index = self._bh_rows_by_height()
                    deletes.extend(self._keys_for_height_scan(h, bh_index))
                    pruned += 1
                    continue
                if meta is None:
                    continue
                deletes.append(_meta_key(h))
                deletes.append(_hash_key(meta.block_id.hash))
                deletes.append(_commit_key(h - 1))
                deletes.append(_seen_commit_key(h))
                for i in range(meta.block_id.part_set_header.total):
                    deletes.append(_part_key(h, i))
                pruned += 1
            self.base = height
            self._db.write_batch([(_STATE_KEY, envelope.wrap(self._state_bytes()))],
                                 deletes)
            return pruned

    def _bh_rows_by_height(self) -> dict[bytes | None, list[bytes]]:
        """One pass over the BH: keyspace: decimal height bytes -> [keys],
        with undecodable rows collected under ``None``."""
        out: dict[bytes | None, list[bytes]] = {}
        for k, v in self._db.iterator(b"BH:", prefix_end(b"BH:")):
            try:
                out.setdefault(envelope.unwrap(v, "block", k), []).append(k)
            except envelope.CorruptedStoreError:
                out.setdefault(None, []).append(k)
        return out

    def _keys_for_height_scan(self, h: int, bh_index: dict) -> list[bytes]:
        """All live rows of one height found by prefix scan (the
        meta-corrupt pruning fallback: part count and block hash are not
        decodable, so enumerate instead of computing). ``bh_index`` is the
        shared :meth:`_bh_rows_by_height` map; undecodable BH rows are
        pruned with the first corrupt height that consults it."""
        keys = [_meta_key(h), _commit_key(h - 1), _seen_commit_key(h)]
        pp = b"P:%020d:" % h
        keys.extend(k for k, _ in self._db.iterator(pp, prefix_end(pp)))
        keys.extend(bh_index.get(str(h).encode(), ()))
        keys.extend(bh_index.pop(None, ()))
        return keys

    def _state_bytes(self) -> bytes:
        return proto.Writer().varint(1, self.base).varint(2, self.height).out()
