"""BlockStore: persists blocks as meta + parts + commits (reference:
store/store.go:93,203,226,248,332).

Layout (one KV row per item, like the reference's calc*Key scheme):
  H:<height>        -> BlockMeta proto
  P:<height>:<idx>  -> Part proto
  C:<height>        -> Commit proto   (LastCommit of height+1)
  SC:<height>       -> Commit proto   (locally seen commit for height)
  BH:<hash>         -> height (decimal)
  blockStore        -> BlockStoreState {base, height}
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field

from tendermint_tpu.encoding import proto
from tendermint_tpu.store.db import DB
from tendermint_tpu.utils import faults
from tendermint_tpu.types.block import Block, Commit, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import Part, PartSet


@dataclass
class BlockMeta:
    """reference: types/block_meta.go."""

    block_id: BlockID = dc_field(default_factory=BlockID)
    block_size: int = 0
    header: Header = dc_field(default_factory=Header)
    num_txs: int = 0

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .message(1, self.block_id.marshal(), always=True)
            .varint(2, self.block_size)
            .message(3, self.header.marshal(), always=True)
            .varint(4, self.num_txs)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "BlockMeta":
        f = proto.fields(buf)
        return BlockMeta(
            block_id=BlockID.unmarshal(f.get(1, [b""])[-1]),
            block_size=proto.as_sint64(f.get(2, [0])[-1]),
            header=Header.unmarshal(f.get(3, [b""])[-1]),
            num_txs=proto.as_sint64(f.get(4, [0])[-1]),
        )


def _meta_key(h: int) -> bytes:
    return b"H:%020d" % h


def _part_key(h: int, i: int) -> bytes:
    return b"P:%020d:%08d" % (h, i)


def _commit_key(h: int) -> bytes:
    return b"C:%020d" % h


def _seen_commit_key(h: int) -> bytes:
    return b"SC:%020d" % h


def _hash_key(block_hash: bytes) -> bytes:
    return b"BH:" + block_hash


_STATE_KEY = b"blockStore"


class BlockStore:
    """Thread-safe; mirrors store/store.go semantics including pruning."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        st = db.get(_STATE_KEY)
        if st is None:
            self.base = 0
            self.height = 0
        else:
            f = proto.fields(st)
            self.base = proto.as_sint64(f.get(1, [0])[-1])
            self.height = proto.as_sint64(f.get(2, [0])[-1])

    # --- accessors ---------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return 0 if self.height == 0 else self.height - self.base + 1

    def load_base_meta(self) -> BlockMeta | None:
        with self._mtx:
            return self.load_block_meta(self.base) if self.base else None

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self._db.get(_meta_key(height))
        return BlockMeta.unmarshal(raw) if raw is not None else None

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            raw = self._db.get(_part_key(height, i))
            if raw is None:
                return None
            parts.append(Part.unmarshal(raw).bytes_)
        return Block.unmarshal(b"".join(parts))

    def load_block_by_hash(self, block_hash: bytes) -> Block | None:
        raw = self._db.get(_hash_key(block_hash))
        if raw is None:
            return None
        return self.load_block(int(raw.decode()))

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self._db.get(_part_key(height, index))
        return Part.unmarshal(raw) if raw is not None else None

    def load_block_commit(self, height: int) -> Commit | None:
        """Commit for `height` stored with block height+1 (reference:
        store/store.go:203)."""
        raw = self._db.get(_commit_key(height))
        return Commit.unmarshal(raw) if raw is not None else None

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self._db.get(_seen_commit_key(height))
        return Commit.unmarshal(raw) if raw is not None else None

    # --- mutation ----------------------------------------------------------

    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """reference: store/store.go:332-383."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        height = block.header.height
        with self._mtx:
            want = self.height + 1
            if self.height > 0 and height != want:
                raise ValueError(f"BlockStore can only save contiguous blocks. Wanted {want}, got {height}")
            if not part_set.is_complete():
                raise ValueError("BlockStore can only save complete block part sets")

            block_id = BlockID(hash=block.hash(), part_set_header=part_set.header())
            meta = BlockMeta(
                block_id=block_id,
                block_size=sum(len(p.bytes_) for p in part_set.parts),
                header=block.header,
                num_txs=len(block.data.txs),
            )
            sets = [(_meta_key(height), meta.marshal()),
                    (_hash_key(block.hash()), str(height).encode())]
            for i, part in enumerate(part_set.parts):
                sets.append((_part_key(height, i), part.marshal()))
            if block.last_commit is not None:
                sets.append((_commit_key(height - 1), block.last_commit.marshal()))
            sets.append((_seen_commit_key(height), seen_commit.marshal()))

            self.height = height
            if self.base == 0:
                self.base = height
            sets.append((_STATE_KEY, self._state_bytes()))
            faults.fire("store.block.save")
            self._db.write_batch(sets)

    def save_seen_commit(self, height: int, seen_commit: Commit) -> None:
        """Standalone seen-commit write for the state-sync bootstrap
        (reference: store/store.go:385 SaveSeenCommit)."""
        with self._mtx:
            self._db.set(_seen_commit_key(height), seen_commit.marshal())

    def prune_blocks(self, height: int) -> int:
        """Removes blocks below `height`, keeping `height` (reference:
        store/store.go:248-330). Returns number pruned."""
        with self._mtx:
            if height <= 0:
                raise ValueError("height must be greater than 0")
            if height > self.height:
                raise ValueError(f"cannot prune beyond the latest height {self.height}")
            if height < self.base:
                return 0
            pruned = 0
            deletes: list[bytes] = []
            for h in range(self.base, height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                deletes.append(_meta_key(h))
                deletes.append(_hash_key(meta.block_id.hash))
                deletes.append(_commit_key(h - 1))
                deletes.append(_seen_commit_key(h))
                for i in range(meta.block_id.part_set_header.total):
                    deletes.append(_part_key(h, i))
                pruned += 1
            self.base = height
            self._db.write_batch([(_STATE_KEY, self._state_bytes())], deletes)
            return pruned

    def _state_bytes(self) -> bytes:
        return proto.Writer().varint(1, self.base).varint(2, self.height).out()
