"""Checksummed record envelope for the storage plane (docs/DURABILITY.md).

Outside the WAL (which has CRC32-framed records and torn-write repair since
PR 1), the stores used to hand back raw DB bytes: a single flipped bit in a
BlockStore part row was either an unhandled proto error inside a reactor
thread or a silently-served bad block.  Every value the stores write is now
framed as::

    0xC5 0x01 <crc32-be, 4 bytes> <payload>

and every read routes through :func:`decode`, which verifies the CRC and
runs the record's unmarshal under a guard — any mismatch or decode blow-up
raises a typed :class:`CorruptedStoreError` naming the store and key, never
a bare struct/proto error.

**Versioned, legacy-compatible.** A value that does not start with the
two-byte magic is treated as a version-0 unframed row and handed to the
decoder as-is, so stores written before the envelope existed keep reading
(no migration step; the next write of the row frames it).  No legacy row in
this tree ever starts with ``0xC5``: proto-encoded rows start with a field
tag (``0x08``/``0x0A``...), BH rows with an ASCII digit, the evidence
committed marker is ``0x01``.

Corruption is *detected* here and *handled* above: the stores invoke their
``on_corruption`` callback (wired to the node's StoreRepairer, which
quarantines the record and schedules repair — store/repair.py) before the
typed error propagates to the caller, so even a caller that only knows how
to crash still leaves the plane self-healing.
"""

from __future__ import annotations

import zlib

MAGIC = b"\xc5\x01"
_HEADER_LEN = len(MAGIC) + 4

# the closed store-label universe: metric labels, scrub report keys, and
# CorruptedStoreError.store values all draw from this tuple
STORES = ("block", "state", "evidence", "txindex")


class CorruptedStoreError(Exception):
    """A store record failed its integrity check (CRC mismatch, truncated
    envelope, or an unmarshal blow-up on the payload). Carries the store
    name, the exact DB key, and — when available — the raw bytes so the
    repairer can quarantine a forensic copy."""

    def __init__(self, store: str, key: bytes, reason: str,
                 raw: bytes | None = None):
        self.store = store
        self.key = key
        self.reason = reason
        self.raw = raw
        super().__init__(
            f"corrupted {store}-store record at key {key!r}: {reason}")


def _hamming2(b0: int, b1: int) -> int:
    return ((b0 ^ MAGIC[0]).bit_count() + (b1 ^ MAGIC[1]).bit_count())


def wrap(payload: bytes) -> bytes:
    """Frame a value for storage: magic + version + CRC32 + payload."""
    return MAGIC + zlib.crc32(payload).to_bytes(4, "big") + payload


def is_framed(raw: bytes) -> bool:
    return raw[:2] == MAGIC


def unwrap(raw: bytes, store: str, key: bytes) -> bytes:
    """Envelope -> payload. Unframed (pre-envelope) rows pass through
    unchanged; a framed row with a bad CRC or a truncated header raises
    :class:`CorruptedStoreError`. Empty rows are corrupt by construction —
    no store writes one, and a truncation-to-nothing must not decode as a
    defaults-filled record."""
    if not raw:
        raise CorruptedStoreError(store, key, "empty record", raw)
    if raw[:2] != MAGIC:
        # a SINGLE bit flip inside the two-byte magic would demote a framed
        # row to the legacy path, where a lenient payload decode might
        # accept the garbage — treat near-magic headers as damaged
        # envelopes instead. (A genuine pre-envelope row starting within
        # Hamming distance 1 of C5 01 is essentially impossible in this
        # tree: proto rows start with a small field tag, BH/index rows with
        # ASCII, docs with '{'.)
        if len(raw) >= 2 and _hamming2(raw[0], raw[1]) <= 1:
            raise CorruptedStoreError(
                store, key, "bit-flipped envelope magic", raw)
        return raw  # version-0 legacy row
    if len(raw) < _HEADER_LEN:
        raise CorruptedStoreError(store, key, "truncated envelope header", raw)
    payload = raw[_HEADER_LEN:]
    want = int.from_bytes(raw[2:_HEADER_LEN], "big")
    got = zlib.crc32(payload)
    if got != want:
        raise CorruptedStoreError(
            store, key, f"crc mismatch (stored {want:08x}, computed {got:08x})",
            raw)
    return payload


def decimal_height(b: bytes) -> int:
    """Strict ASCII-decimal decode for height-valued rows (BH:, blkh/ and
    blk/ postings). Bare ``int(b.decode())`` accepts b" 2\\n" or b"1_0"
    (Python allows whitespace and underscores), which would let a damaged
    short row decode leniently on the legacy path."""
    s = b.decode("ascii")
    if not s.isdigit():
        raise ValueError(f"height row is {b!r}, want ASCII decimal")
    return int(s)


def decode(raw: bytes, store: str, key: bytes, fn, on_corruption=None):
    """The checked read path every store load routes through: unwrap the
    envelope, then run ``fn(payload)`` under a guard so a bit flip that
    survives into the payload of a LEGACY (unframed) row still surfaces as
    the typed error, not a bare proto/struct exception.  ``on_corruption``
    (the store's repairer hook) fires once per detection, and must never
    itself raise into the read path."""
    try:
        payload = unwrap(raw, store, key)
        return fn(payload)
    except CorruptedStoreError as e:
        _note(e, on_corruption)
        raise
    except Exception as e:  # noqa: BLE001 - any decode blow-up IS corruption
        err = CorruptedStoreError(store, key, f"decode failed: {e!r}", raw)
        _note(err, on_corruption)
        raise err from e


def _note(err: CorruptedStoreError, on_corruption) -> None:
    count_detection(err.store)
    if on_corruption is not None:
        try:
            on_corruption(err)
        except Exception:  # noqa: BLE001 - the hook is best-effort; the
            # typed error still propagates to the caller either way
            pass


def count_detection(store: str) -> None:
    """Bump the pre-seeded `store_corruption_detected_total{store}` counter
    (utils/metrics.py) when a node has metrics enabled."""
    try:
        from tendermint_tpu.utils import metrics as tmmetrics

        m = tmmetrics.GLOBAL_NODE_METRICS
        if m is not None:
            m.store_corruption_detected.add(1, store=store)
    except Exception:  # noqa: BLE001 - metrics must never block a read
        pass


def count_repair(store: str) -> None:
    try:
        from tendermint_tpu.utils import metrics as tmmetrics

        m = tmmetrics.GLOBAL_NODE_METRICS
        if m is not None:
            m.store_corruption_repaired.add(1, store=store)
    except Exception:  # noqa: BLE001
        pass


# --- quarantine --------------------------------------------------------------

QUARANTINE_PREFIX = b"Q:"


def quarantine(db, err: CorruptedStoreError) -> None:
    """Move the corrupt record out of the live keyspace: a forensic copy
    lands under ``Q:<key>`` and the original is deleted, so every later
    read sees *missing* (handled everywhere) instead of *corrupt* — the
    store never serves the bad bytes twice."""
    raw = err.raw if err.raw is not None else db.get(err.key)
    if raw is not None:
        db.set(QUARANTINE_PREFIX + err.key, raw)
    db.delete(err.key)


def quarantined_keys(db) -> list[bytes]:
    """Original keys of every quarantined record (forensics / tests)."""
    from tendermint_tpu.store.db import prefix_end

    return [k[len(QUARANTINE_PREFIX):] for k, _ in
            db.iterator(QUARANTINE_PREFIX, prefix_end(QUARANTINE_PREFIX))]
