"""Storage-plane scrubber: walk every store, verify every record, report —
and optionally hand the damage to the repairer (docs/DURABILITY.md).

The scrubber is the offline/startup/on-demand half of the self-healing
plane: where the envelope (store/envelope.py) catches corruption lazily on
the next read, a scrub pass proactively decodes EVERY row of the block,
state, evidence, and tx-index stores — so at-rest bit rot is found before
a peer asks for the block, and the operator gets a full damage map from
one ``unsafe_scrub`` RPC call instead of a trickle of read errors.

Structure checks beyond the CRC:

* block store: every height in ``[base, height]`` must have a decodable
  meta, all ``part_set_header.total`` parts, and a BH index row that
  points back at it; dangling BH rows (pruning leftovers, stale hashes)
  are flagged.  Heights below ``base`` are a **pruned gap — healthy**, not
  corruption.
* state store: the state row plus every validator / consensus-params /
  ABCI-responses history row decodes; full validator rows unmarshal to a
  ValidatorSet.
* evidence / tx-index: every row decodes under its expected shape.

Detected corruption is quarantined on the spot (the record moves to the
``Q:`` keyspace, so nothing can serve it) and, when a
:class:`~tendermint_tpu.store.repair.StoreRepairer` is supplied, scheduled
for repair — blocks re-fetched from peers and batch-kernel re-verified,
state rebuilt from the block store, index rows re-derived.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from tendermint_tpu.encoding import proto
from tendermint_tpu.store import envelope
from tendermint_tpu.store import block_store as bs_mod
from tendermint_tpu.store.db import prefix_end
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.part_set import Part
from tendermint_tpu.utils import trace as _trace


@dataclass
class Corruption:
    store: str
    key: bytes
    reason: str
    height: int | None = None

    def describe(self) -> str:
        at = f" (height {self.height})" if self.height is not None else ""
        return f"{self.store}:{self.key!r}{at}: {self.reason}"


@dataclass
class ScrubReport:
    checked: int = 0
    corruptions: list = field(default_factory=list)   # [Corruption]
    repaired: list = field(default_factory=list)      # [str]
    unrepaired: list = field(default_factory=list)    # [str]
    pruned_gap_heights: int = 0
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.corruptions

    @property
    def healthy_after_repair(self) -> bool:
        return not self.unrepaired

    def as_dict(self) -> dict:
        return {
            "checked": self.checked,
            "corruptions": [c.describe() for c in self.corruptions],
            "repaired": list(self.repaired),
            "unrepaired": list(self.unrepaired),
            "pruned_gap_heights": self.pruned_gap_heights,
            "duration_s": round(self.duration_s, 4),
            "ok": self.ok,
        }


class Scrubber:
    """One pass over a node's stores. Every store handle is optional so the
    scrubber composes with partial wiring (offline tools, tests, nodes
    without an indexer)."""

    def __init__(self, block_store=None, state_store=None, evidence_db=None,
                 txindex_db=None, tracer=None):
        self.block_store = block_store
        self.state_store = state_store
        self.evidence_db = evidence_db
        self.txindex_db = txindex_db
        self.tracer = tracer

    # --- the pass -----------------------------------------------------------

    def scrub(self, repairer=None, repair_timeout_s: float = 10.0,
              drain: bool = True) -> ScrubReport:
        """Walk everything; quarantine + report every bad record. With a
        ``repairer``, schedule each finding and — unless ``drain=False``
        (startup / soak: let the background worker retry once peers exist)
        — synchronously drain the repair queue (peer fetches bounded by
        ``repair_timeout_s``). Without a repairer the quarantine is
        PERMANENT for everything except the presence-only evidence
        committed markers (restored inline: their loss would re-open a
        double-commit window) — that mode is for the offline matrix and
        diagnostics; every production caller supplies the node's
        repairer."""
        report = ScrubReport()
        t0 = time.monotonic()
        tracer = self.tracer if self.tracer is not None else _trace.current()
        with tracer.span("store.scrub"):
            if self.block_store is not None:
                self._scrub_block_store(report)
            if self.state_store is not None:
                self._scrub_state_store(report)
            if self.evidence_db is not None:
                self._scrub_simple(report, self.evidence_db, "evidence")
            if self.txindex_db is not None:
                self._scrub_simple(report, self.txindex_db, "txindex")
            if repairer is not None and report.corruptions:
                for c in report.corruptions:
                    repairer.note(envelope.CorruptedStoreError(
                        c.store, c.key, c.reason), spawn=not drain)
                if drain:
                    done, failed = repairer.repair_pending(
                        timeout_s=repair_timeout_s)
                    report.repaired = done
                    report.unrepaired = failed
            elif report.corruptions:
                self._restore_evidence_markers(report)
        report.duration_s = time.monotonic() - t0
        try:
            from tendermint_tpu.utils import metrics as tmmetrics

            if tmmetrics.GLOBAL_NODE_METRICS is not None:
                tmmetrics.GLOBAL_NODE_METRICS.store_scrub_runs.add(1)
        except Exception:  # noqa: BLE001 - metrics never gate a scrub
            pass
        return report

    def _restore_evidence_markers(self, report: ScrubReport) -> None:
        """No-repairer quarantine must not eat `c:<hash>` committed
        markers: `is_committed` tests key PRESENCE only, so a missing
        marker re-opens a double-commit window for that evidence. The
        value is a constant and the key carries all the data — the restore
        is exact and needs no repairer (repair.py's
        _restore_committed_marker does the same on the scheduled path)."""
        if self.evidence_db is None:
            return
        for c in report.corruptions:
            if c.store == "evidence" and c.key.startswith(b"c"):
                self.evidence_db.set(c.key, envelope.wrap(b"\x01"))
                envelope.count_repair("evidence")
                report.repaired.append(f"evidence_marker:{c.key!r}")

    # --- per-store walks ----------------------------------------------------

    def _flag(self, report: ScrubReport, db, store: str, key: bytes,
              reason: str, height: int | None = None,
              raw: bytes | None = None) -> None:
        report.corruptions.append(Corruption(store, key, reason, height))
        err = envelope.CorruptedStoreError(store, key, reason, raw)
        envelope.count_detection(store)
        if raw is not None or db.get(key) is not None:
            envelope.quarantine(db, err)

    def _check(self, report: ScrubReport, db, store: str, key: bytes,
               raw: bytes, fn, height: int | None = None) -> object | None:
        """Decode one row; on failure flag + quarantine, return None."""
        report.checked += 1
        try:
            return fn(envelope.unwrap(raw, store, key))
        except envelope.CorruptedStoreError as e:
            self._flag(report, db, store, key, e.reason, height, raw)
        except Exception as e:  # noqa: BLE001 - decode blow-up IS corruption
            self._flag(report, db, store, key, f"decode failed: {e!r}",
                       height, raw)
        return None

    def _scrub_block_store(self, report: ScrubReport) -> None:
        bs = self.block_store
        db = bs._db
        base, height = bs.base, bs.height
        if height == 0:
            return  # nothing ever saved: a fresh store is healthy
        report.pruned_gap_heights = max(0, base - 1)
        hash_to_height: dict[bytes, int] = {}
        for h in range(max(base, 1), height + 1):
            if h < bs.base:
                continue  # pruned while the scrub was walking: healthy gap
            mkey = bs_mod._meta_key(h)
            raw = db.get(mkey)
            meta = None
            if raw is None:
                if h < bs.base:
                    continue  # prune_blocks won the race for this height
                self._flag(report, db, "block", mkey, "missing meta row", h)
            else:
                meta = self._check(report, db, "block", mkey, raw,
                                   bs_mod.BlockMeta.unmarshal, h)
            if meta is None:
                # the meta can no longer vouch for part count or hash:
                # decode whatever rows the height still has by prefix scan
                pp = b"P:%020d:" % h
                for k, v in list(db.iterator(pp, prefix_end(pp))):
                    self._check(report, db, "block", k, v, Part.unmarshal, h)
                for ckey in (bs_mod._commit_key(h),
                             bs_mod._seen_commit_key(h)):
                    craw = db.get(ckey)
                    if craw is not None:
                        self._check(report, db, "block", ckey, craw,
                                    Commit.unmarshal, h)
                continue
            hash_to_height[meta.block_id.hash] = h
            for i in range(meta.block_id.part_set_header.total):
                pkey = bs_mod._part_key(h, i)
                praw = db.get(pkey)
                if praw is None:
                    if h >= bs.base:  # not a concurrent prune: real damage
                        self._flag(report, db, "block", pkey,
                                   "missing part row", h)
                    continue
                part = self._check(report, db, "block", pkey, praw,
                                   Part.unmarshal, h)
                if part is not None and len(part.bytes_) == 0:
                    self._flag(report, db, "block", pkey, "empty part", h)
            for ckey in (bs_mod._commit_key(h), bs_mod._seen_commit_key(h)):
                craw = db.get(ckey)
                if craw is not None:
                    self._check(report, db, "block", ckey, craw,
                                Commit.unmarshal, h)
            bh_key = bs_mod._hash_key(meta.block_id.hash)
            braw = db.get(bh_key)
            if braw is None:
                if h >= bs.base:
                    self._flag(report, db, "block", bh_key,
                               "missing BH index row", h)
            else:
                got = self._check(report, db, "block", bh_key, braw,
                                  envelope.decimal_height, h)
                if got is not None and got != h:
                    self._flag(report, db, "block", bh_key,
                               f"BH index points at {got}, expected {h}", h)
        # dangling BH rows: an index entry must resolve to a live height
        # whose meta carries the same hash (stale rows from the pruning
        # path or rot in the hash bytes themselves). The walk above used a
        # base/height SNAPSHOT, but the default-on boot scrub runs while
        # consensus keeps committing and pruning — so re-read the live
        # bounds here: a block committed after the snapshot is healthy
        # growth, not an "unknown height", and a height pruned mid-scrub
        # legitimately lost its rows.
        for k, v in list(db.iterator(b"BH:", prefix_end(b"BH:"))):
            try:
                h = envelope.decimal_height(envelope.unwrap(v, "block", k))
            except Exception:  # noqa: BLE001 - flagged above if in range
                continue
            if h > height:
                if h > bs.height:
                    self._flag(report, db, "block", k,
                               f"BH index row for unknown height {h}", h)
            elif h < bs.base:
                if db.get(k) is not None:  # survived its height's pruning
                    self._flag(report, db, "block", k,
                               f"BH index row for pruned height {h}", h)
            elif h >= base and hash_to_height.get(k[3:]) != h:
                self._flag(report, db, "block", k,
                           f"dangling BH index row -> height {h}", h)

    def _scrub_state_store(self, report: ScrubReport) -> None:
        from tendermint_tpu.state import store as ss_mod

        ss = self.state_store
        db = ss._db
        raw = db.get(b"stateKey")
        if raw is not None:
            self._check(report, db, "state", b"stateKey", raw,
                        ss_mod._unmarshal_state)
        for prefix, label in ((b"validatorsKey:", "validators"),
                              (b"consensusParamsKey:", "params"),
                              (b"abciResponsesKey:", "abci")):
            for k, v in list(db.iterator(prefix, prefix_end(prefix))):
                h = _height_suffix(k)
                if label == "abci":
                    # the exact decoder the read path runs — top-level
                    # proto.fields would pass rot inside a nested
                    # ResponseDeliverTx that load_abci_responses rejects
                    self._check(report, db, "state", k, v,
                                ss_mod.ABCIResponses.unmarshal, h)
                    continue
                f = self._check(report, db, "state", k, v, proto.fields, h)
                if f is None:
                    continue
                if 1 in f:
                    try:
                        if label == "validators":
                            from tendermint_tpu.types.validator_set import (
                                ValidatorSet)

                            ValidatorSet.unmarshal(f[1][-1])
                        else:
                            from tendermint_tpu.types.params import (
                                ConsensusParams)

                            ConsensusParams.unmarshal(f[1][-1])
                    except Exception as e:  # noqa: BLE001
                        self._flag(report, db, "state", k,
                                   f"{label} payload decode failed: {e!r}",
                                   h, v)

    def _scrub_simple(self, report: ScrubReport, db, store: str) -> None:
        if store == "evidence":
            from tendermint_tpu.types.evidence import evidence_unmarshal

            for k, v in list(db.iterator(b"p", b"q")):
                self._check(report, db, store, k, v, evidence_unmarshal)
            for k, v in list(db.iterator(b"c", b"d")):
                self._check(report, db, store, k, v, _committed_marker)
            return
        import json

        from tendermint_tpu.state.txindex import _height_str, _posting_hash

        for k, v in list(db.iterator(b"txr/", prefix_end(b"txr/"))):
            self._check(report, db, store, k, v, json.loads)
        for k, v in list(db.iterator(b"txe/", prefix_end(b"txe/"))):
            self._check(report, db, store, k, v, _posting_hash)
        for prefix in (b"blk/", b"blkh/"):
            for k, v in list(db.iterator(prefix, prefix_end(prefix))):
                self._check(report, db, store, k, v, _height_str)


def _committed_marker(b: bytes) -> bytes:
    """Strict decode of the evidence committed marker: exactly b"\x01".
    Anything else (e.g. a magic-byte flip demoting a framed row to the
    legacy path) is corruption."""
    if b != b"\x01":
        raise ValueError(f"committed marker is {b!r}, want b'\\x01'")
    return b


def _height_suffix(key: bytes) -> int | None:
    try:
        return int(key.rsplit(b":", 1)[-1])
    except ValueError:
        return None


def scrub_on_start_enabled() -> bool:
    """TMTPU_SCRUB_ON_START gates the node's boot-time scrub pass
    (default on; `0` skips it — docs/CONFIG.md)."""
    import os

    return os.environ.get("TMTPU_SCRUB_ON_START", "1") != "0"
