"""Embedded key-value store: the tm-db equivalent.

The reference depends on github.com/tendermint/tm-db (go.mod:43) with
pluggable backends (goleveldb default, cleveldb/rocksdb/boltdb/badgerdb);
selection via Config.DBBackend (node/node.go:76-79). Here: "memdb" (tests,
ephemeral) and "sqlite" (durable, stdlib, WAL-mode) behind the same
interface. Iteration is byte-ordered like tm-db's.
"""

from __future__ import annotations

import abc
import bisect
import os
import sqlite3
import threading
from pathlib import Path


class DB(abc.ABC):
    @abc.abstractmethod
    def get(self, key: bytes) -> bytes | None: ...

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def iterator(self, start: bytes | None = None, end: bytes | None = None):
        """Yield (key, value) ascending for start <= key < end."""

    @abc.abstractmethod
    def reverse_iterator(self, start: bytes | None = None, end: bytes | None = None):
        """Yield (key, value) descending for start <= key < end."""

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def write_batch(self, sets: list[tuple[bytes, bytes]], deletes: list[bytes] = ()) -> None:
        for k, v in sets:
            self.set(k, v)
        for k in deletes:
            self.delete(k)

    def close(self) -> None:
        pass


class MemDB(DB):
    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def iterator(self, start=None, end=None):
        with self._lock:
            lo = 0 if start is None else bisect.bisect_left(self._keys, start)
            hi = len(self._keys) if end is None else bisect.bisect_left(self._keys, end)
            keys = self._keys[lo:hi]
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def reverse_iterator(self, start=None, end=None):
        with self._lock:
            lo = 0 if start is None else bisect.bisect_left(self._keys, start)
            hi = len(self._keys) if end is None else bisect.bisect_left(self._keys, end)
            keys = self._keys[lo:hi]
        for k in reversed(keys):
            v = self.get(k)
            if v is not None:
                yield k, v


class SQLiteDB(DB):
    """Durable backend on stdlib sqlite3 (WAL mode, fsync on commit).

    Durability policy is ``PRAGMA synchronous`` — ``NORMAL`` by default:
    in WAL mode a commit is fsynced only at WAL checkpoints, so an OS
    crash / power loss can roll the DB back to the last checkpoint
    (application-level recovery — consensus WAL replay + fast-sync —
    absorbs that window). ``TMTPU_DB_SYNC=full`` pins ``synchronous=FULL``
    (every commit fsyncs the WAL: no power-loss window, slower writes).
    See docs/CONFIG.md."""

    def __init__(self, path: str) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        sync = os.environ.get("TMTPU_DB_SYNC", "normal").strip().lower()
        if sync not in ("normal", "full"):
            raise ValueError(
                f"TMTPU_DB_SYNC={sync!r} (want 'normal' or 'full')")
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA synchronous={sync.upper()}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (key, value),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def write_batch(self, sets, deletes=()) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                list(sets),
            )
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k = ?", [(k,) for k in deletes])
            self._conn.commit()

    def iterator(self, start=None, end=None):
        q, params = self._range_query(start, end, "ASC")
        with self._lock:
            rows = self._conn.execute(q, params).fetchall()
        yield from rows

    def reverse_iterator(self, start=None, end=None):
        q, params = self._range_query(start, end, "DESC")
        with self._lock:
            rows = self._conn.execute(q, params).fetchall()
        yield from rows

    @staticmethod
    def _range_query(start, end, order):
        q = "SELECT k, v FROM kv"
        conds, params = [], []
        if start is not None:
            conds.append("k >= ?")
            params.append(start)
        if end is not None:
            conds.append("k < ?")
            params.append(end)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += f" ORDER BY k {order}"
        return q, params

    def close(self) -> None:
        with self._lock:
            self._conn.commit()
            try:
                # fsync-on-close: fold the WAL back into the main DB file
                # and sync it, so a clean shutdown leaves no replay window
                # regardless of the synchronous level above
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:
                pass  # a reader holding the WAL open only defers the fold
            self._conn.close()


def prefix_end(prefix: bytes) -> bytes | None:
    """Smallest key greater than every key with this prefix."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return None


def new_db(backend: str, path: str | None = None) -> DB:
    if backend == "memdb":
        return MemDB()
    if backend == "sqlite":
        if path is None:
            raise ValueError("sqlite backend needs a path")
        return SQLiteDB(path)
    raise ValueError(f"unknown db backend {backend!r}")
