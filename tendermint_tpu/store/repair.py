"""Peer-assisted storage repair: quarantine, re-fetch, re-verify, rewrite
(docs/DURABILITY.md).

The repairer is where a corruption detection (store/envelope.py) turns
into healing instead of a crash:

* **block rows** (meta / parts / commits / BH index): the block is
  re-fetched from peers over the fast-sync wire protocol (BlockRequest on
  channel 0x40 — the same machinery the pool uses), re-verified against
  this node's OWN validator set and a trusted commit through
  ``ValidatorSet.verify_commit_light`` (one batched kernel call), and only
  then rewritten. A peer can never talk a node into accepting different
  bytes: the commit signatures pin the block hash.
* **state rows**: the full state row is rebuilt from the block store
  (rollback-style reconstruction at tip-1; the startup handshake replays
  the final block through the app — "replay-from-blockstore"). When the
  block store cannot support the rebuild the verdict is
  ``needs_statesync`` and the node's normal state-sync bootstrap path
  takes over. Unambiguously re-derivable history rows are rewritten;
  anything else stays quarantined (reads see *missing*, never rot).
* **evidence rows**: for pending evidence, quarantine IS repair — it
  regossips from peers. The committed ``c:<hash>`` marker is rewritten in
  place: its value is a constant and ``is_committed`` only tests key
  presence, so leaving it quarantined would re-open a double-commit
  window for that evidence.
* **tx-index rows**: tx documents and event postings (``txr/``, ``txe/``,
  ``blkh/``) are re-indexed from the block + ABCI-responses stores when
  both are wired. Block-event postings (``blk/``) are NOT re-derivable —
  ABCIResponses persists only the DeliverTx results, so begin/end-block
  events exist nowhere else — and stay quarantined.

Detection sites call :meth:`StoreRepairer.note` (the stores'
``on_corruption`` hook): it quarantines immediately — the record can never
be served twice — and schedules the repair on a lazy background worker
(spawned on first damage, so an undamaged node pays zero threads).
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.store import envelope
from tendermint_tpu.store import block_store as bs_mod
from tendermint_tpu.utils import trace as _trace

FETCH_TIMEOUT_S = 3.0
MAX_ATTEMPTS = 8


def _task_key(store: str, key: bytes) -> tuple:
    """(kind, arg) repair task for one corrupt record's key."""
    if store == "block":
        if key.startswith((b"H:", b"P:", b"SC:")):
            return ("block", int(key.split(b":")[1]))
        if key.startswith(b"C:"):
            return ("block", int(key.split(b":")[1]))
        if key.startswith(b"BH:"):
            return ("block_hash_row", key[3:])
        return ("noop", key)  # blockStore row self-heals in the constructor
    if store == "state":
        if key == b"stateKey":
            return ("state", None)
        if key.startswith(b"validatorsKey:"):
            return ("state_val", int(key.rsplit(b":", 1)[-1]))
        if key.startswith(b"consensusParamsKey:"):
            return ("state_params", int(key.rsplit(b":", 1)[-1]))
        return ("state_abci", key)  # not re-derivable: quarantine only
    if store == "evidence":
        if key.startswith(b"c"):
            # presence-only marker: restore it or the quarantine itself
            # re-opens a double-commit window (is_committed -> False)
            return ("evidence_marker", key)
        return ("noop", key)  # pending: drop IS repair (regossip)
    if store == "txindex":
        parts = key.split(b"/")
        if key.startswith(b"txr/"):
            # the doc key carries no height, but the surviving tx.height
            # posting's VALUE is this hash — the repair scans for it
            return ("txindex_doc", key[4:])
        if key.startswith(b"txe/") and len(parts) >= 5:
            return ("txindex", int(parts[3]))
        if key.startswith(b"blkh/") and len(parts) >= 2:
            try:
                return ("txindex", int(parts[-1]))
            except ValueError:
                return ("txindex_row", key)
        if key.startswith(b"blk/"):
            # block-event postings aren't persisted anywhere else (the
            # ABCI-responses row carries only DeliverTx results): not
            # re-derivable, quarantine is final
            return ("txindex_row", key)
        return ("txindex_row", key)  # doc row: height unknowable, drop
    return ("noop", key)


class StoreRepairer:
    """Owns quarantine + the repair queue for one node's storage plane."""

    def __init__(self, block_store=None, state_store=None, chain_id: str = "",
                 evidence_db=None, tx_indexer=None, block_indexer=None,
                 logger=None, tracer=None):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.evidence_db = evidence_db
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.switch = None          # wired by the node once p2p exists
        self.logger = logger
        self.tracer = tracer
        self.needs_statesync = False
        self.repaired_total = 0
        self._lock = threading.Lock()
        self._pending: dict[tuple, int] = {}   # task -> attempts
        self._failed: list[str] = []
        self._waiters: dict[int, list] = {}    # height -> [(Event, [Block])]
        self._worker: threading.Thread | None = None
        self._wake = threading.Event()

    # --- detection entry (the stores' on_corruption hook) -------------------

    def note(self, err: envelope.CorruptedStoreError,
             spawn: bool = True) -> None:
        """Quarantine the record and schedule its repair. Idempotent and
        non-blocking: safe to fire from any read path. ``spawn=False``
        queues without waking the background worker (the scrubber drains
        synchronously right after scheduling)."""
        db = self._db_for(err.store)
        if db is not None:
            try:
                envelope.quarantine(db, err)
            except Exception:  # noqa: BLE001 - quarantine is best-effort;
                # the read already failed typed, scheduling still happens
                pass
        task = _task_key(err.store, err.key)
        if task[0] == "noop":
            return
        if self.logger is not None:
            self.logger.error("store corruption quarantined", store=err.store,
                              key=repr(err.key), reason=err.reason)
        with self._lock:
            self._pending.setdefault(task, 0)
            if spawn:
                self._ensure_worker_locked()
        if spawn:
            self._wake.set()

    def _db_for(self, store: str):
        if store == "block" and self.block_store is not None:
            return self.block_store._db
        if store == "state" and self.state_store is not None:
            return self.state_store._db
        if store == "evidence":
            return self.evidence_db
        if store == "txindex" and self.tx_indexer is not None:
            return self.tx_indexer._db
        return None

    # --- background worker (lazy: zero threads until first damage) ----------

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._worker_loop,
                                            name="store-repair", daemon=True)
            self._worker.start()

    def _worker_loop(self) -> None:
        backoff = 0.2
        while True:
            try:
                self._wake.wait(timeout=backoff)
                self._wake.clear()
                done, _failed = self.repair_pending(timeout_s=FETCH_TIMEOUT_S)
                with self._lock:
                    if not self._pending:
                        self._worker = None
                        return
                backoff = 0.2 if done else min(backoff * 2, 5.0)
            except Exception as e:  # noqa: BLE001 - the repair loop must
                # survive anything (peer churn, store races); retry later
                if self.logger is not None:
                    self.logger.error("store repair pass failed", err=e)
                time.sleep(0.5)

    # --- synchronous drain (scrubber, unsafe_scrub RPC, tests) --------------

    def pending(self) -> list[tuple]:
        with self._lock:
            return sorted(self._pending)

    def repair_pending(self, timeout_s: float = 10.0) -> tuple[list, list]:
        """Attempt every scheduled repair once (peer fetches bounded by
        ``timeout_s`` each). Returns (repaired descriptions, failed-this-
        pass descriptions); failures stay queued until MAX_ATTEMPTS. An
        attempt may return ``None`` — "can't try yet" (p2p is wired but no
        peer is connected, the boot-scrub window) — which keeps the task
        queued WITHOUT burning an attempt, so a corruption detected before
        the first peer handshake still heals once peers arrive instead of
        exhausting its budget against an empty switch."""
        with self._lock:
            tasks = sorted(self._pending)
        done: list[str] = []
        failed: list[str] = []
        for task in tasks:
            kind, arg = task
            label = f"{kind}:{arg!r}"
            try:
                ok = self._attempt(kind, arg, timeout_s)
            except Exception as e:  # noqa: BLE001 - one broken repair must
                # not abandon the rest of the queue
                ok = False
                label = f"{label} ({e!r})"
            with self._lock:
                if ok:
                    self._pending.pop(task, None)
                    done.append(label)
                elif ok is None:  # no peers yet: retry later, free of charge
                    failed.append(label)
                else:
                    self._pending[task] = self._pending.get(task, 0) + 1
                    if self._pending[task] >= MAX_ATTEMPTS:
                        self._pending.pop(task, None)
                        self._failed.append(label)
                    failed.append(label)
        return done, failed

    def _attempt(self, kind: str, arg, timeout_s: float) -> bool:
        if kind == "block":
            return self.repair_block_height(int(arg), timeout_s=timeout_s)
        if kind == "block_hash_row":
            return self._repair_block_hash_row(arg)
        if kind == "state":
            return self.repair_state()
        if kind == "state_val":
            return self._repair_validators_row(int(arg))
        if kind == "state_params":
            return self._repair_params_row(int(arg))
        if kind == "state_abci":
            return True  # not re-derivable; quarantined = handled
        if kind == "evidence_marker":
            return self._restore_committed_marker(arg)
        if kind == "txindex":
            return self._reindex_height(int(arg))
        if kind == "txindex_doc":
            return self._reindex_doc(arg)
        if kind == "txindex_row":
            return True  # blk/ posting quarantined; not re-derivable
        return True

    def _repaired(self, store: str) -> bool:
        self.repaired_total += 1
        envelope.count_repair(store)
        return True

    # --- block repair: re-fetch from peers, batch-verify, rewrite -----------

    def repair_block_height(self, height: int,
                            timeout_s: float = FETCH_TIMEOUT_S):
        """Restore every row of one damaged height. The rewritten block is
        ALWAYS re-verified before it touches the store: its hash must be
        signed by +2/3 of this node's own validator set at that height
        (``verify_commit_light`` — the batched kernel path), and must match
        the intact local meta/commit when one survives. Returns True on
        repaired/nothing-to-heal, False on a failed (counted) attempt, and
        None when a peer fetch is needed but no peer is connected yet."""
        bs = self.block_store
        if bs is None or not (bs.base <= height <= bs.height):
            return bs is not None  # outside the live range: nothing to heal
        tracer = self.tracer if self.tracer is not None else _trace.current()
        with tracer.span("store.repair", height=height):
            return self._repair_block_locked(height, timeout_s)

    def _repair_block_locked(self, height: int, timeout_s: float):
        from tendermint_tpu.types.part_set import PartSet

        bs = self.block_store
        meta = self._quiet(bs.load_block_meta, height)
        commit = (self._quiet(bs.load_block_commit, height)
                  or self._quiet(bs.load_seen_commit, height))
        local = self._quiet(bs.load_block, height)
        if local is None or commit is None:
            peers = self._connected_peers()
            if peers is not None and not peers:
                return None  # p2p wired but nobody connected (boot scrub /
                # partition): retry later without burning an attempt
        candidates = ([local] if local is not None
                      else self._fetch_blocks(height, timeout_s))
        candidates = [b for b in candidates if b.header.height == height]
        if not candidates:
            return False
        if commit is not None:
            commits = [commit]
        else:
            nxt = self._quiet(bs.load_block, height + 1)
            nxts = ([nxt] if nxt is not None
                    else self._fetch_blocks(height + 1, timeout_s))
            commits = [n.last_commit for n in nxts
                       if n.header.height == height + 1
                       and n.last_commit is not None]
        if not commits:
            return False
        # every candidate is tried: a garbage (or malicious) fastest
        # responder fails _verify_block and the honest copy behind it in
        # the window still repairs this very attempt
        seen: set = set()
        for block in candidates:
            bh = block.hash()
            if bh in seen:
                continue
            seen.add(bh)
            for c in commits:
                if not self._verify_block(block, c, meta):
                    continue
                part_set = PartSet.from_data(block.marshal())
                if not bs.rewrite_block(block, part_set, c):
                    return True  # pruned while the fetch was in flight:
                    # nothing left to heal, and no rows may be re-laid
                return self._repaired("block")
        return False

    def _verify_block(self, block, commit, meta) -> bool:
        height = block.header.height
        if commit.height != height or commit.block_id.hash != block.hash():
            return False
        if meta is not None and meta.block_id.hash != block.hash():
            return False  # a peer cannot replace a block we still know
        if self.state_store is None:
            return meta is not None  # no valset: only the meta-pinned case
        try:
            vals = self.state_store.load_validators(height)
            vals.verify_commit_light(self.chain_id, commit.block_id,
                                     height, commit)
            return True
        except Exception as e:  # noqa: BLE001 - unverifiable = unrepaired
            if self.logger is not None:
                self.logger.error("block repair verify failed",
                                  height=height, err=e)
            return False

    def _repair_block_hash_row(self, block_hash: bytes) -> bool:
        """Re-derive one BH index row by scanning metas for the hash."""
        bs = self.block_store
        if bs is None:
            return False
        for h in range(bs.base, bs.height + 1):
            meta = self._quiet(bs.load_block_meta, h)
            if meta is not None and meta.block_id.hash == block_hash:
                bs._db.set(bs_mod._hash_key(block_hash),
                           envelope.wrap(str(h).encode()))
                return self._repaired("block")
        return True  # no live height carries it: quarantined row was stale

    def _connected_peers(self):
        """Connected-peer snapshot, or None when no p2p is wired at all
        (offline tools / pure-scrub repairers, which should fail fast
        rather than wait for peers that can never come)."""
        sw = self.switch
        if sw is None:
            return None
        with sw._peers_mtx:
            return list(sw.peers.values())

    _FETCH_GRACE_S = 0.25
    _MAX_OFFERS = 8

    def _fetch_blocks(self, height: int, timeout_s: float) -> list:
        """One bounded peer fetch over the fast-sync wire protocol. The
        blockchain reactor's receive() feeds BlockResponse messages to
        :meth:`offer_block`. EVERY response landing in the window is
        collected (first response opens a short straggler grace) so a
        fast garbage responder cannot crowd out honest copies — the
        caller verifies each candidate; verification, not arrival order,
        picks the winner."""
        peers = self._connected_peers()
        if not peers:
            return []
        from tendermint_tpu.blockchain import reactor as bc

        ev = threading.Event()
        slot: list = []
        with self._lock:
            self._waiters.setdefault(height, []).append((ev, slot))
        try:
            for p in peers[:4]:
                p.try_send(bc.BLOCKCHAIN_CHANNEL, bc.msg_block_request(height))
            deadline = time.monotonic() + timeout_s
            while not slot:
                left = deadline - time.monotonic()
                if left <= 0 or not ev.wait(left):
                    break
                ev.clear()
            if slot:  # let slower honest responses join the candidate set
                time.sleep(min(self._FETCH_GRACE_S,
                               max(0.0, deadline - time.monotonic())))
            with self._lock:
                return list(slot)
        finally:
            with self._lock:
                ws = self._waiters.get(height, [])
                if (ev, slot) in ws:
                    ws.remove((ev, slot))
                if not ws:
                    self._waiters.pop(height, None)

    def offer_block(self, peer_id: str, block) -> bool:
        """Called by the blockchain reactors for every BlockResponse: hand
        the block to any repair fetch waiting on its height. Returns True
        when a waiter consumed it."""
        if not self._waiters:  # lock-free fast path: fast sync delivers
            return False       # thousands of responses with nobody waiting
        h = getattr(getattr(block, "header", None), "height", None)
        if h is None:
            return False
        with self._lock:
            ws = list(self._waiters.get(h, ()))
            for ev, slot in ws:
                if len(slot) < self._MAX_OFFERS:
                    slot.append(block)
                ev.set()
        return bool(ws)

    # --- state repair: replay-from-blockstore / statesync verdict -----------

    def repair_state(self) -> bool:
        ss, bs = self.state_store, self.block_store
        if ss is None:
            return False
        st = self._quiet(ss.load)
        if st is not None and st.last_block_height > 0:
            return True  # a later save already rewrote the row
        rebuilt = rebuild_state_from_blockstore(ss, bs) if bs is not None else None
        if rebuilt is None:
            # the block store cannot support a rebuild: hand the verdict to
            # the node's state-sync bootstrap (docs/DURABILITY.md)
            self.needs_statesync = True
            return bool(bs is None or bs.height == 0)
        from tendermint_tpu.state import store as ss_mod

        ss._set(b"stateKey", ss_mod._marshal_state(rebuilt))
        return self._repaired("state")

    def _repair_validators_row(self, height: int) -> bool:
        """Rewrite one validator-history row from unambiguous sources: the
        live state row's three sets (tip window), or a NEXT-row back-pointer
        that proves nothing changed at ``height``. Anything ambiguous stays
        quarantined (reads raise ErrNoValSetForHeight — missing, not rot)."""
        ss = self.state_store
        if ss is None:
            return False
        st = self._quiet(ss.load)
        if st is not None and st.last_block_height > 0:
            tip = st.last_block_height
            window = {tip: st.last_validators, tip + 1: st.validators,
                      tip + 2: st.next_validators}
            vals = window.get(height)
            if vals is not None and not vals.is_nil_or_empty():
                ss.rewrite_validators(height, height, vals)
                return self._repaired("state")
        nxt = self._quiet(ss.validators_last_changed, height + 1)
        if nxt is not None and nxt < height:
            ss.rewrite_validators(height, nxt, None)
            return self._repaired("state")
        return True  # quarantined; not re-derivable without ambiguity

    def _repair_params_row(self, height: int) -> bool:
        ss = self.state_store
        if ss is None:
            return False
        st = self._quiet(ss.load)
        if st is not None and st.last_block_height > 0:
            if height == st.last_block_height + 1:
                ss._save_params(height, height, st.consensus_params)
                return self._repaired("state")
        return True  # quarantined; later loads fall back typed-missing

    # --- evidence repair ----------------------------------------------------

    def _restore_committed_marker(self, key: bytes) -> bool:
        """Rewrite the canonical ``c:<hash>`` committed marker. Its value
        is a constant and ``EvidencePool.is_committed`` only tests key
        PRESENCE, so the row's rot was harmless — but the quarantine
        deleted the key, which would let the same evidence commit twice.
        The key itself carries all the data; restoring it is exact."""
        if self.evidence_db is None:
            return True  # nothing wired; nothing to restore into
        self.evidence_db.set(key, envelope.wrap(b"\x01"))
        return self._repaired("evidence")

    # --- tx-index repair ----------------------------------------------------

    def _reindex_height(self, height: int) -> bool:
        """Re-derive the tx documents, event postings, and blkh/ row of one
        height from the block + ABCI-responses stores (those rows are pure
        functions of them; blk/ block-event postings are not — see
        _task_key — and never reach here)."""
        if (self.tx_indexer is None or self.block_store is None
                or self.state_store is None):
            return True  # nothing wired to rebuild into; quarantine stands
        block = self._quiet(self.block_store.load_block, height)
        if block is None:
            return True  # pruned height: stale index rows stay quarantined
        try:
            resp = self.state_store.load_abci_responses(height)
        except Exception:  # noqa: BLE001 - responses gone: quarantine stands
            return True
        for i, tx in enumerate(block.data.txs):
            result = (resp.deliver_txs[i] if i < len(resp.deliver_txs)
                      else None)
            self.tx_indexer.index(height, i, tx, result)
        if self.block_indexer is not None:
            self.block_indexer.index(height, [], [])
        return self._repaired("txindex")

    def _reindex_doc(self, tx_hash: bytes) -> bool:
        """Recover a quarantined ``txr/`` document: the tx.height posting's
        VALUE is this hash, so an intact posting names the height to
        re-derive. No surviving posting => quarantine stands."""
        if self.tx_indexer is None:
            return True
        from tendermint_tpu.store.db import prefix_end

        prefix = b"txe/tx.height/"
        for k, v in list(self.tx_indexer._db.iterator(prefix,
                                                      prefix_end(prefix))):
            try:
                if envelope.unwrap(v, "txindex", k) != tx_hash:
                    continue
                height = int(k.split(b"/")[3])
            except Exception:  # noqa: BLE001 - a rotten posting has its
                continue       # own repair task; skip it here
            return self._reindex_height(height)
        return True

    @staticmethod
    def _quiet(fn, *args):
        """A load that treats corrupt exactly like missing (the hook has
        already quarantined + scheduled it)."""
        try:
            return fn(*args)
        except Exception:  # noqa: BLE001
            return None


# --- state reconstruction ----------------------------------------------------


def rebuild_state_from_blockstore(state_store, block_store):
    """Rollback-style reconstruction of the state row at tip-1 from intact
    block-store + state-history rows (state/rollback.py mirrored forward):
    ``app_hash`` after tip-1 is carried by the tip header, so the rebuilt
    row is exact, and the startup handshake replays the final block through
    the app to reach the tip ("replay-from-blockstore"). Returns None when
    the block store cannot support the rebuild (empty, pruned past tip-1,
    or its own rows are damaged) — the caller falls back to a state-sync
    re-bootstrap."""
    from dataclasses import replace as _replace

    from tendermint_tpu.state.state import State

    h = block_store.height
    if h < 2 or block_store.base > h - 1:
        return None
    try:
        tip_meta = block_store.load_block_meta(h)
        prev_meta = block_store.load_block_meta(h - 1)
        if tip_meta is None or prev_meta is None:
            return None
        target = h - 1
        last_vals = state_store.load_validators(target)
        curr_vals = state_store.load_validators(target + 1)
        next_vals = state_store.load_validators(target + 2)
        params = state_store.load_consensus_params(target + 1)
        vals_changed = state_store.validators_last_changed(target + 1)
        params_changed = state_store.params_last_changed(target + 1)
    except Exception:  # noqa: BLE001 - any gap means no exact rebuild
        return None
    return _replace(
        State(),
        version=tip_meta.header.version,
        chain_id=tip_meta.header.chain_id,
        last_block_height=target,
        last_block_id=prev_meta.block_id,
        last_block_time=prev_meta.header.time,
        validators=curr_vals,
        next_validators=next_vals,
        last_validators=last_vals,
        last_height_validators_changed=vals_changed or target + 1,
        consensus_params=params,
        last_height_consensus_params_changed=params_changed or target + 1,
        app_hash=tip_meta.header.app_hash,
        # results(target) live in the TIP header (header h commits the
        # results of h-1); prev_meta's would be results(target-1) and the
        # handshake's replay of the tip block would fail validate_block
        last_results_hash=tip_meta.header.last_results_hash,
    )


def recover_state(state_store, block_store, logger=None,
                  statesync_enabled: bool = False):
    """Node-construction guard around the very first ``StateStore.load()``:
    a corrupt state row is quarantined and rebuilt from the block store
    when possible; otherwise an empty State comes back, which routes the
    node into the normal bootstrap — genesis + full replay when the block
    store is unpruned, state-sync (it activates on last_block_height == 0)
    when enabled. A PRUNED block store with state sync disabled refuses to
    boot typed instead: the handshake would silently replay from ``base``
    into a fresh app, skipping heights ``1..base-1`` and diverging."""
    try:
        return state_store.load()
    except envelope.CorruptedStoreError as err:
        rebuilt = rebuild_state_from_blockstore(state_store, block_store)
        pruned = (block_store is not None and block_store.height > 0
                  and block_store.base > 1)
        if rebuilt is None and pruned and not statesync_enabled:
            # refuse BEFORE quarantining: deleting the row would make the
            # next boot see *missing*, take the genesis path, and diverge
            # silently — leave it so every retry fails typed until the
            # operator enables statesync or restores from backup
            raise envelope.CorruptedStoreError(
                "state", b"stateKey",
                "state row unrebuildable and the block store is pruned "
                f"(base {block_store.base}): genesis replay cannot cover "
                "the gap — enable statesync to re-bootstrap, or restore "
                "from backup", err.raw) from err
        envelope.quarantine(state_store._db, err)
        if rebuilt is not None:
            from tendermint_tpu.state import store as ss_mod

            state_store._set(b"stateKey", ss_mod._marshal_state(rebuilt))
            envelope.count_repair("state")
            if logger is not None:
                logger.error("state row corrupt; rebuilt from block store",
                             height=rebuilt.last_block_height)
            return rebuilt
        if logger is not None:
            logger.error("state row corrupt and not rebuildable; "
                         "falling back to bootstrap", err=str(err))
        from tendermint_tpu.state.state import State

        return State()
