"""Handshaker: syncs the ABCI app with the block store on startup
(reference: consensus/replay.go:241,284,437).

On restart the app may be behind (crash between SaveBlock and Commit) or
fresh (empty app behind a populated chain): replay stored blocks through the
app until app height == store height.
"""

from __future__ import annotations

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state import execution as sm_exec
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import ABCIResponses, StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet


class HandshakeError(Exception):
    pass


class Handshaker:
    def __init__(self, state_store: StateStore, block_store: BlockStore,
                 genesis: GenesisDoc, logger=None):
        self.state_store = state_store
        self.block_store = block_store
        self.genesis = genesis
        self.logger = logger
        self.n_blocks = 0

    def handshake(self, state: State, app) -> State:
        """reference: consensus/replay.go:241-284."""
        res = app.info(abci.RequestInfo(version="0.34.24-tpu"))
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"got a negative last block height ({app_height}) from the app")
        return self.replay_blocks(state, app, app_hash, app_height)

    def replay_blocks(self, state: State, app, app_hash: bytes, app_height: int) -> State:
        """reference: consensus/replay.go:284-437."""
        store_height = self.block_store.height
        state_height = state.last_block_height

        # InitChain if the app is at height 0.
        if app_height == 0:
            validators = [
                Validator.new(v.pub_key, v.power) for v in self.genesis.validators
            ]
            req = abci.RequestInitChain(
                time_seconds=self.genesis.genesis_time.seconds,
                time_nanos=self.genesis.genesis_time.nanos,
                chain_id=self.genesis.chain_id,
                consensus_params=self.genesis.consensus_params,
                validators=[
                    abci.ValidatorUpdate(v.pub_key.type, v.pub_key.bytes(), v.voting_power)
                    for v in validators
                ],
                app_state_bytes=self.genesis.app_state,
                initial_height=self.genesis.initial_height,
            )
            res = app.init_chain(req)
            if store_height == 0:
                # apply InitChain response to state (reference: replay.go:330-370)
                if res.app_hash:
                    state.app_hash = res.app_hash
                    app_hash = res.app_hash
                if res.validators:
                    vals = sm_exec.validator_updates_from_abci(res.validators)
                    state.validators = ValidatorSet(vals)
                    state.next_validators = ValidatorSet(vals)
                    state.next_validators.increment_proposer_priority(1)
                elif not self.genesis.validators:
                    raise HandshakeError("validator set is nil in genesis and still empty after InitChain")
                if res.consensus_params is not None:
                    state.consensus_params = res.consensus_params
                self.state_store.save(state)

        if store_height == 0:
            return state

        # replay any blocks the app is missing
        if app_height < store_height:
            state = self._replay_range(state, app, app_height, store_height)
        elif app_height == store_height:
            if state_height == store_height - 1:
                # Crashed between ABCI Commit and the state save (fail-point 4):
                # the app already executed the final block, so update the state
                # from the saved ABCI responses WITHOUT re-executing on the real
                # app (reference: consensus/replay.go:419-428 mock-app replay).
                state = self._mock_replay_last_block(state, app_hash)
        else:
            raise HandshakeError(
                f"app block height ({app_height}) is higher than the chain ({store_height})"
            )
        return state

    def _mock_replay_last_block(self, state: State, app_hash: bytes) -> State:
        """Apply the stored ABCI responses of the final block to the state
        without touching the app (reference: consensus/replay.go:419-428,516
        via newMockProxyApp)."""
        from dataclasses import replace

        h = self.block_store.height
        block = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if block is None or meta is None:
            raise HandshakeError(f"missing block at height {h} for mock replay")
        try:
            responses = self.state_store.load_abci_responses(h)
        except Exception as e:
            raise HandshakeError(
                f"no saved ABCI responses for height {h}; cannot sync state "
                f"without re-executing the committed block"
            ) from e
        sm_exec.validate_validator_updates(
            responses.end_block.validator_updates, state.consensus_params)
        validator_updates = sm_exec.validator_updates_from_abci(
            responses.end_block.validator_updates)
        new_state = sm_exec.update_state(
            state, meta.block_id, block, responses, validator_updates)
        new_state = replace(new_state, app_hash=app_hash)
        self.state_store.save(new_state)
        return new_state

    def _replay_range(self, state: State, app, app_height: int, store_height: int) -> State:
        """Replay blocks [app_height+1, store_height] through the app
        (reference: consensus/replay.go:437-530 replayBlocks/replayBlock)."""
        from tendermint_tpu.store.envelope import CorruptedStoreError

        first = max(app_height + 1, self.block_store.base)
        for h in range(first, store_height + 1):
            try:
                block = self.block_store.load_block(h)
            except CorruptedStoreError as e:
                # quarantined by the store hook; replay cannot proceed past
                # a rotten block the app still needs — fail typed so the
                # operator (or a statesync re-bootstrap) takes over rather
                # than crashing on a bare proto error (docs/DURABILITY.md)
                raise HandshakeError(
                    f"block at height {h} is corrupt and required for app "
                    f"replay: {e}") from e
            if block is None:
                raise HandshakeError(f"missing block at height {h} during replay")
            meta = self.block_store.load_block_meta(h)
            if state.last_block_height < h:
                # full apply through BlockExecutor (also saves state)
                bx = sm_exec.BlockExecutor(self.state_store, app,
                                           block_store=self.block_store)
                state, _ = bx.apply_block(state, meta.block_id, block)
            else:
                # state is ahead: app-only replay (exec + commit, no state save)
                self._exec_block_app_only(state, app, block, meta.block_id)
            self.n_blocks += 1
        return state

    def _exec_block_app_only(self, state: State, app, block, block_id: BlockID) -> None:
        commit_info = sm_exec.get_begin_block_validator_info(
            block, self.state_store, state.initial_height)
        app.begin_block(abci.RequestBeginBlock(
            hash=block.hash() or b"", header=block.header,
            last_commit_info=commit_info))
        # the shared deliver engine (docs/EXECUTION.md): handshake replay
        # produces the same app hashes through the batched path as the
        # serial loop, chunking and fallback included
        sm_exec.deliver_block_txs(app, block.data.txs)
        app.end_block(abci.RequestEndBlock(height=block.header.height))
        app.commit()
