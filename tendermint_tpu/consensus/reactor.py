"""Consensus reactor: gossips round state, proposals, block parts, and votes
(reference: consensus/reactor.go:142 channels, :199-201 per-peer gossip
routines, :1065+ PeerState).

Channels (priorities as in reference GetChannels):
  State 0x20 (prio 6), Data 0x21 (10), Vote 0x22 (7), VoteSetBits 0x23 (1).

Wire: tendermint.consensus.Message oneof (proto/tendermint/consensus/types.proto).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.consensus import cstypes
from tendermint_tpu.consensus.state_machine import ConsensusState, commit_to_vote_set
from tendermint_tpu.encoding import proto
from tendermint_tpu.utils.bits import BitArray
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import Part, PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23


# --- bit array wire helpers (proto/tendermint/libs/bits/types.proto) --------


def bits_marshal(bits) -> bytes:
    """Any iterable of bools or a BitArray -> proto bits encoding."""
    if not isinstance(bits, BitArray):
        bits = BitArray.from_bools(list(bits))
    return bits.marshal()


def bits_unmarshal(buf: bytes) -> BitArray:
    return BitArray.unmarshal(buf)


# --- message codecs ----------------------------------------------------------


def _wrap(field_num: int, body: bytes) -> bytes:
    return proto.Writer().message(field_num, body, always=True).out()


def msg_new_round_step(height, round_, step, secs_since_start, last_commit_round) -> bytes:
    return _wrap(1, proto.Writer().varint(1, height).varint(2, round_)
                 .uvarint(3, step).varint(4, secs_since_start)
                 .varint(5, last_commit_round).out())


def msg_new_valid_block(height, round_, psh: PartSetHeader, parts_bits, is_commit) -> bytes:
    return _wrap(2, proto.Writer().varint(1, height).varint(2, round_)
                 .message(3, psh.marshal(), always=True)
                 .message(4, bits_marshal(parts_bits))
                 .bool(5, is_commit).out())


def msg_proposal(p: Proposal) -> bytes:
    return _wrap(3, proto.Writer().message(1, p.marshal(), always=True).out())


def msg_block_part(height, round_, part: Part) -> bytes:
    return _wrap(5, proto.Writer().varint(1, height).varint(2, round_)
                 .message(3, part.marshal(), always=True).out())


def msg_vote(v: Vote) -> bytes:
    return _wrap(6, proto.Writer().message(1, v.marshal(), always=True).out())


def msg_has_vote(height, round_, type_, index) -> bytes:
    return _wrap(7, proto.Writer().varint(1, height).varint(2, round_)
                 .varint(3, type_).varint(4, index).out())


def msg_vote_set_maj23(height, round_, type_, block_id: BlockID) -> bytes:
    return _wrap(8, proto.Writer().varint(1, height).varint(2, round_)
                 .varint(3, type_).message(4, block_id.marshal(), always=True).out())


def msg_vote_set_bits(height, round_, type_, block_id: BlockID, votes_bits) -> bytes:
    return _wrap(9, proto.Writer().varint(1, height).varint(2, round_)
                 .varint(3, type_).message(4, block_id.marshal(), always=True)
                 .message(5, bits_marshal(votes_bits), always=True).out())


# --- per-peer state (reference: consensus/reactor.go:1065 PeerState) --------


@dataclass
class PeerRoundState:
    height: int = 0
    round: int = -1
    step: int = 0
    proposal: bool = False
    proposal_block_psh: PartSetHeader | None = None
    proposal_block_parts: BitArray = field(default_factory=BitArray)
    proposal_pol_round: int = -1
    prevotes: dict[int, BitArray] = field(default_factory=dict)      # round -> bits
    precommits: dict[int, BitArray] = field(default_factory=dict)
    last_commit_round: int = -1
    last_commit: BitArray = field(default_factory=BitArray)
    catchup_commit_round: int = -1
    catchup_commit: BitArray = field(default_factory=BitArray)


class PeerState:
    def __init__(self, peer: Peer):
        self.peer = peer
        self.prs = PeerRoundState()
        self.mtx = threading.RLock()
        self.running = True

    def apply_new_round_step(self, height, round_, step, last_commit_round, n_vals) -> None:
        with self.mtx:
            prs = self.prs
            init_height = prs.height
            if prs.height != height or prs.round != round_:
                prs.proposal = False
                prs.proposal_block_psh = None
                prs.proposal_block_parts = BitArray()
                prs.proposal_pol_round = -1
            if prs.height != height:
                if prs.height + 1 == height and prs.round == last_commit_round:
                    prs.last_commit_round = last_commit_round
                    prs.last_commit = prs.precommits.get(last_commit_round, BitArray())
                else:
                    prs.last_commit_round = last_commit_round
                    prs.last_commit = BitArray()
                prs.prevotes = {}
                prs.precommits = {}
                prs.catchup_commit_round = -1
                prs.catchup_commit = BitArray()
            prs.height = height
            prs.round = round_
            prs.step = step
            _ = init_height

    def set_has_proposal(self, proposal: Proposal) -> None:
        with self.mtx:
            prs = self.prs
            if prs.height != proposal.height or prs.round != proposal.round:
                return
            if prs.proposal:
                return
            prs.proposal = True
            if not prs.proposal_block_parts:  # otherwise NewValidBlock set it
                prs.proposal_block_psh = proposal.block_id.part_set_header
                prs.proposal_block_parts = BitArray(proposal.block_id.part_set_header.total)
            prs.proposal_pol_round = proposal.pol_round

    def set_has_block_part(self, height, round_, index) -> None:
        with self.mtx:
            prs = self.prs
            if prs.height != height or prs.round != round_:
                return
            if 0 <= index < len(prs.proposal_block_parts):
                prs.proposal_block_parts[index] = True

    def set_has_vote(self, height, round_, type_, index, n_vals) -> None:
        with self.mtx:
            bits = self._votes_bits(height, round_, type_, n_vals)
            if bits is not None and 0 <= index < len(bits):
                bits[index] = True

    def _votes_bits(self, height, round_, type_, n_vals) -> BitArray | None:
        prs = self.prs
        if prs.height == height:
            table = prs.prevotes if type_ == PREVOTE_TYPE else prs.precommits
            if round_ not in table and round_ in (prs.round, prs.round + 1,
                                                 prs.catchup_commit_round):
                table[round_] = BitArray(n_vals)
            return table.get(round_)
        if prs.height == height + 1 and type_ == PRECOMMIT_TYPE and round_ == prs.last_commit_round:
            if not prs.last_commit:
                prs.last_commit = BitArray(n_vals)
            return prs.last_commit
        return None


# --- the reactor -------------------------------------------------------------


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState, wait_sync: bool = False):
        super().__init__("CONSENSUS")
        self.cs = cs
        self.wait_sync = wait_sync  # True while fast sync is running
        self._peer_states: dict[str, PeerState] = {}
        self._mtx = threading.RLock()
        cs.on_new_round_step.append(self._broadcast_new_round_step)
        cs.on_vote.append(self._broadcast_has_vote)
        cs.on_valid_block.append(self._broadcast_new_valid_block)
        cs.broadcast = self._cs_broadcast

    def get_channels(self) -> list[ChannelDescriptor]:
        """reference: consensus/reactor.go:142-178."""
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=7),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1),
        ]

    def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Called by the fast-sync reactor when caught up (reference:
        consensus/reactor.go:108-140)."""
        if state.last_block_height > self.cs.state.last_block_height:
            # Reconstruct LastCommit from the stored seen commit (reference:
            # reactor.go:120 reconstructLastCommit): whatever rs.last_commit
            # held belongs to a height fast sync just skipped past, and a
            # stale vote set must never be packed into a future proposal.
            if state.last_block_height > 0:
                try:
                    seen = self.cs.block_store.load_seen_commit(
                        state.last_block_height)
                except CorruptedStoreError:
                    seen = None  # quarantined; consensus restarts without
                    # the reconstructed LastCommit (same as missing)
                if seen is not None and state.last_validators is not None:
                    self.cs.rs.last_commit = commit_to_vote_set(
                        state.chain_id, seen, state.last_validators)
            self.cs.update_to_state(state)
        self.wait_sync = False
        self.cs.start()

    # --- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer: Peer) -> None:
        ps = PeerState(peer)
        with self._mtx:
            self._peer_states[peer.id] = ps
        peer.set("consensus_peer_state", ps)
        # ONE gossip thread per peer (was three: data, votes, maj23 each
        # owned a thread). Per-peer thread count is the limiting resource
        # for the in-process scenario fabric (e2e/fabric.py budgets it at
        # PER_PEER_THREADS per link side); the three loops all poll on the
        # same peer-gossip cadence, so they share one loop with the maj23
        # pass kept on its own slower clock.
        threading.Thread(target=self._gossip_routine, args=(peer, ps),
                         daemon=True).start()
        if not self.wait_sync:
            self._send_new_round_step(peer)

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._mtx:
            ps = self._peer_states.pop(peer.id, None)
        if ps is not None:
            ps.running = False

    # --- receive -----------------------------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        ps: PeerState = peer.get("consensus_peer_state")
        if ps is None:
            return
        f = proto.fields(msg_bytes)
        n_vals = self.cs.rs.validators.size() if self.cs.rs.validators else 0
        if ch_id == STATE_CHANNEL:
            if 1 in f:  # NewRoundStep
                m = proto.fields(f[1][-1])
                height = proto.as_sint64(m.get(1, [0])[-1])
                round_ = proto.as_sint64(m.get(2, [0])[-1])
                step = m.get(3, [0])[-1]
                lcr = proto.as_sint64(m.get(5, [0])[-1])
                ps.apply_new_round_step(height, round_, step, lcr, n_vals)
            elif 2 in f:  # NewValidBlock
                m = proto.fields(f[2][-1])
                with ps.mtx:
                    if ps.prs.height == proto.as_sint64(m.get(1, [0])[-1]):
                        ps.prs.proposal_block_psh = PartSetHeader.unmarshal(m.get(3, [b""])[-1])
                        ps.prs.proposal_block_parts = bits_unmarshal(m.get(4, [b""])[-1]) if 4 in m else []
            elif 7 in f:  # HasVote
                m = proto.fields(f[7][-1])
                ps.set_has_vote(
                    proto.as_sint64(m.get(1, [0])[-1]),
                    proto.as_sint64(m.get(2, [0])[-1]),
                    proto.as_sint64(m.get(3, [0])[-1]),
                    proto.as_sint64(m.get(4, [0])[-1]),
                    n_vals,
                )
            elif 8 in f:  # VoteSetMaj23
                m = proto.fields(f[8][-1])
                height = proto.as_sint64(m.get(1, [0])[-1])
                round_ = proto.as_sint64(m.get(2, [0])[-1])
                type_ = proto.as_sint64(m.get(3, [0])[-1])
                bid = BlockID.unmarshal(m.get(4, [b""])[-1])
                self._handle_vote_set_maj23(peer, ps, height, round_, type_, bid)
        elif ch_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if 3 in f:  # Proposal
                m = proto.fields(f[3][-1])
                p = Proposal.unmarshal(m.get(1, [b""])[-1])
                ps.set_has_proposal(p)
                self.cs.set_proposal(p, peer_id=peer.id)
            elif 4 in f:  # ProposalPOL
                m = proto.fields(f[4][-1])
                with ps.mtx:
                    if ps.prs.height == proto.as_sint64(m.get(1, [0])[-1]):
                        ps.prs.proposal_pol_round = proto.as_sint64(m.get(2, [0])[-1])
            elif 5 in f:  # BlockPart
                m = proto.fields(f[5][-1])
                height = proto.as_sint64(m.get(1, [0])[-1])
                round_ = proto.as_sint64(m.get(2, [0])[-1])
                part = Part.unmarshal(m.get(3, [b""])[-1])
                ps.set_has_block_part(height, round_, part.index)
                self.cs.add_proposal_block_part(height, round_, part, peer_id=peer.id)
        elif ch_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if 6 in f:
                m = proto.fields(f[6][-1])
                vote = Vote.unmarshal(m.get(1, [b""])[-1])
                ps.set_has_vote(vote.height, vote.round, vote.type,
                                vote.validator_index, n_vals)
                self.cs.add_vote(vote, peer_id=peer.id)
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if 9 in f:
                m = proto.fields(f[9][-1])
                # peer tells us which votes it has for a maj23
                height = proto.as_sint64(m.get(1, [0])[-1])
                round_ = proto.as_sint64(m.get(2, [0])[-1])
                type_ = proto.as_sint64(m.get(3, [0])[-1])
                bits = bits_unmarshal(m.get(5, [b""])[-1]) if 5 in m else []
                with ps.mtx:
                    table = ps.prs.prevotes if type_ == PREVOTE_TYPE else ps.prs.precommits
                    if height == ps.prs.height:
                        existing = table.get(round_)
                        if existing is None:
                            table[round_] = bits
                        else:
                            for i, b in enumerate(bits[: len(existing)]):
                                existing[i] = existing[i] or b

    def _handle_vote_set_maj23(self, peer, ps, height, round_, type_, bid) -> None:
        """reference: consensus/reactor.go:300-340."""
        rs = self.cs.rs
        if rs.height != height or rs.votes is None:
            return
        try:
            if type_ == PREVOTE_TYPE:
                votes = rs.votes.prevotes(round_)
            else:
                votes = rs.votes.precommits(round_)
            if votes is None:
                return
            votes.set_peer_maj23(peer.id, bid)
            our_bits = votes.bit_array_by_block_id(bid) or []
            peer.try_send(VOTE_SET_BITS_CHANNEL,
                          msg_vote_set_bits(height, round_, type_, bid, our_bits))
        except Exception:  # noqa: BLE001
            pass

    # --- broadcasts from our own state machine ------------------------------

    def _cs_broadcast(self, msg) -> None:
        """Internally-generated proposal/parts/votes: peers get them via the
        gossip routines; nothing to do eagerly (reference relies on gossip).
        Votes additionally trigger HasVote broadcasts via on_vote."""

    def _broadcast_new_round_step(self, rs) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(STATE_CHANNEL, self._new_round_step_msg(rs))

    def _broadcast_new_valid_block(self, rs) -> None:
        if self.switch is None or rs.proposal_block_parts is None:
            return
        self.switch.broadcast(STATE_CHANNEL, msg_new_valid_block(
            rs.height, rs.round, rs.proposal_block_parts.header(),
            rs.proposal_block_parts.bit_array(), rs.step == cstypes.STEP_COMMIT))

    def _broadcast_has_vote(self, vote: Vote) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(STATE_CHANNEL, msg_has_vote(
            vote.height, vote.round, vote.type, vote.validator_index))

    def _new_round_step_msg(self, rs) -> bytes:
        import time as _t

        secs = max(0, int(_t.time() - rs.start_time.seconds)) if rs.start_time else 0
        lcr = rs.last_commit.round if rs.last_commit is not None else -1
        return msg_new_round_step(rs.height, rs.round, rs.step, secs, lcr)

    def _send_new_round_step(self, peer: Peer) -> None:
        peer.try_send(STATE_CHANNEL, self._new_round_step_msg(self.cs.rs))

    # --- gossip routines (reference: consensus/reactor.go:540-1050) --------

    def _gossip_routine(self, peer: Peer, ps: PeerState) -> None:
        """The per-peer gossip loop: data (proposal/parts) + votes each
        pass, the VoteSetMaj23 query on its own slower cadence. Busy
        passes (something sent) loop immediately; idle passes sleep one
        peer-gossip interval — same observable behavior as the former
        three dedicated threads at a third of the thread bill."""
        try:
            maj23_sleep = self.cs.config.peer_query_maj23_sleep_duration_s
            next_maj23 = time.monotonic() + maj23_sleep
            while ps.running and self.switch is not None:
                if self.wait_sync:
                    time.sleep(0.1)
                    continue
                sent = self._gossip_data_step(peer, ps)
                sent = self._gossip_votes_step(peer, ps) or sent
                now = time.monotonic()
                if now >= next_maj23:
                    next_maj23 = now + maj23_sleep
                    self._query_maj23_step(peer, ps)
                if not sent:
                    time.sleep(self.cs.config.peer_gossip_sleep_duration_s)
        except Exception as e:  # noqa: BLE001 - a gossip-thread death ends
            # like a disconnect (peer teardown mid-send starts a fresh
            # routine on re-add), but a systematic bug here would silently
            # starve the peer of proposals and votes — leave a trail
            logger = getattr(self.switch, "logger", None)
            if logger:
                logger.error("consensus gossip routine ended",
                             peer=peer.id, err=e)

    def _gossip_data_step(self, peer: Peer, ps: PeerState) -> bool:
        """One data-gossip pass; True when something was sent."""
        rs = self.cs.rs
        prs = ps.prs
        # send block parts the peer lacks for the current proposal
        if (rs.proposal_block_parts is not None and prs.height == rs.height
                and prs.proposal_block_psh == rs.proposal_block_parts.header()):
            ours = rs.proposal_block_parts.bit_array()
            theirs = prs.proposal_block_parts
            want = [i for i, have in enumerate(ours)
                    if have and (i >= len(theirs) or not theirs[i])]
            if want:
                i = random.choice(want)
                part = rs.proposal_block_parts.get_part(i)
                if part is not None and peer.try_send(
                        DATA_CHANNEL, msg_block_part(rs.height, rs.round, part)):
                    ps.set_has_block_part(prs.height, prs.round, i)
                    return True
        # catchup: peer is on an older height -> send stored block parts
        elif (0 < prs.height < rs.height
              and prs.height >= self.cs.block_store.base):
            return self._gossip_data_for_catchup(peer, ps)
        # send proposal
        if (rs.proposal is not None and prs.height == rs.height
                and prs.round == rs.round and not prs.proposal):
            if peer.try_send(DATA_CHANNEL, msg_proposal(rs.proposal)):
                ps.set_has_proposal(rs.proposal)
                return True
        return False

    def _gossip_data_for_catchup(self, peer: Peer, ps: PeerState) -> bool:
        """reference: consensus/reactor.go:631-700. True when a part was
        sent (the caller's loop owns the idle sleep)."""
        prs = ps.prs
        try:
            meta = self.cs.block_store.load_block_meta(prs.height)
        except CorruptedStoreError:
            return False  # quarantined + repair scheduled by the store hook
        if meta is None:
            return False
        with ps.mtx:
            if prs.proposal_block_psh != meta.block_id.part_set_header:
                prs.proposal_block_psh = meta.block_id.part_set_header
                prs.proposal_block_parts = BitArray(meta.block_id.part_set_header.total)
            want = [i for i, have in enumerate(prs.proposal_block_parts) if not have]
        if not want:
            return False
        i = random.choice(want)
        try:
            part = self.cs.block_store.load_block_part(prs.height, i)
        except CorruptedStoreError:
            # never gossip a rotten part; the repair hook already has the
            # height, and a healed part flows on a later pass
            return False
        if part is None:
            return False
        if peer.try_send(DATA_CHANNEL, msg_block_part(prs.height, prs.round, part)):
            ps.set_has_block_part(prs.height, prs.round, i)
            return True
        return False

    def _gossip_votes_step(self, peer: Peer, ps: PeerState) -> bool:
        """One vote-gossip pass; True when a vote was sent."""
        rs = self.cs.rs
        if rs.votes is None:
            return False
        return self._pick_send_vote(peer, ps, rs, ps.prs)

    def _pick_send_vote(self, peer, ps, rs, prs) -> bool:
        """Pick one vote the peer lacks and send it (reference:
        consensus/reactor.go:716-830 gossipVotesRoutine + PickSendVote)."""
        def send_from(vote_set, their_bits) -> bool:
            if vote_set is None:
                return False
            for i, v in enumerate(vote_set.votes):
                if v is None:
                    continue
                if their_bits is not None and i < len(their_bits) and their_bits[i]:
                    continue
                if peer.try_send(VOTE_CHANNEL, msg_vote(v)):
                    ps.set_has_vote(v.height, v.round, v.type, i,
                                    vote_set.val_set.size())
                    return True
                return False
            return False

        if prs.height == rs.height:
            # current round prevotes/precommits + POL prevotes
            if prs.proposal_pol_round >= 0:
                pv = rs.votes.prevotes(prs.proposal_pol_round)
                if send_from(pv, prs.prevotes.get(prs.proposal_pol_round)):
                    return True
            pv = rs.votes.prevotes(prs.round) if prs.round >= 0 else None
            if send_from(pv, prs.prevotes.get(prs.round)):
                return True
            pc = rs.votes.precommits(prs.round) if prs.round >= 0 else None
            if send_from(pc, prs.precommits.get(prs.round)):
                return True
        if prs.height + 1 == rs.height and rs.last_commit is not None:
            # Peer is one height behind: send last-commit precommits. For the
            # peer these are CURRENT-height precommits, so the have-bits live
            # in prs.precommits[commit round] (reference: PeerState
            # getVoteBitArray, consensus/reactor.go:1170-1210).
            if send_from(rs.last_commit, prs.precommits.get(rs.last_commit.round)):
                return True
        if prs.height < rs.height and prs.height >= max(self.cs.block_store.base, 1):
            # catchup: send precommits from the stored commit
            try:
                commit = self.cs.block_store.load_block_commit(prs.height)
            except CorruptedStoreError:
                commit = None  # quarantined; repair scheduled
            if commit is not None:
                with ps.mtx:
                    # EnsureCatchupCommitRound (reference: reactor.go:1120-1140)
                    prs.catchup_commit_round = commit.round
                their_bits = prs.precommits.get(commit.round)
                for i, cs_sig in enumerate(commit.signatures):
                    if cs_sig.absent():
                        continue
                    if their_bits and i < len(their_bits) and their_bits[i]:
                        continue
                    vote = commit.get_vote(i)
                    if peer.try_send(VOTE_CHANNEL, msg_vote(vote)):
                        ps.set_has_vote(vote.height, vote.round, vote.type, i,
                                        len(commit.signatures))
                        return True
                    return False
        return False

    def _query_maj23_step(self, peer: Peer, ps: PeerState) -> None:
        """One VoteSetMaj23 announcement pass (reference:
        consensus/reactor.go:870-950); paced by _gossip_routine's
        peer_query_maj23_sleep_duration_s clock."""
        rs = self.cs.rs
        prs = ps.prs
        if rs.votes is None or prs.height != rs.height:
            return
        for type_, vs in ((PREVOTE_TYPE, rs.votes.prevotes(prs.round)),
                          (PRECOMMIT_TYPE, rs.votes.precommits(prs.round))):
            if vs is None:
                continue
            maj, ok = vs.two_thirds_majority()
            if ok:
                peer.try_send(STATE_CHANNEL,
                              msg_vote_set_maj23(rs.height, prs.round, type_, maj))
