"""Consensus stall watchdog: detect no-commit progress behind a partition
and hand the node back to fast-sync catchup (no reference analogue — the
reference node spins rounds forever when it falls behind a healed
partition until consensus catchup gossip drags it forward height by
height; the verify-ahead fast-sync pipeline is a far faster road home).

Detection: the committed height (block_store.height) has not advanced for
``config.watchdog_stall_s()`` seconds AND some peer reports a height at
least ``config.watchdog_peer_lead`` ahead. Peer heights come from both
live sources a node already maintains: the consensus reactor's per-peer
round state (NewRoundStep gossip) and the fast-sync pool's status
responses. Both are push-updated, so within moments of a heal the majority
side's lead is visible here.

The peer-lead requirement is what makes the watchdog safe: a node that is
merely partitioned (peers stale or absent) must NOT thrash into fast sync
— there is nothing to sync from. Only the combination "I am stalled AND a
reachable peer is provably ahead" triggers the hand-back, and recovery is
the node's own fast-sync + verify-ahead machinery, not a restart.

Metrics (wired through utils/metrics.py when instrumentation is on):
``tendermint_consensus_stalled`` gauge (1 while stalled) and
``tendermint_consensus_watchdog_recoveries_total`` counter.
"""

from __future__ import annotations

import threading
import time


class ConsensusWatchdog:
    """Monitors one node; ``recover_fn`` is Node.handoff_to_fastsync."""

    def __init__(self, config, block_store, consensus_reactor, bc_reactor,
                 recover_fn, metrics=None, logger=None,
                 check_interval_s: float = 0.25):
        self.config = config
        self.block_store = block_store
        self.consensus_reactor = consensus_reactor
        self.bc_reactor = bc_reactor
        self.recover_fn = recover_fn
        self.metrics = metrics
        self.logger = logger
        self.check_interval_s = check_interval_s
        self.recoveries = 0
        self.stalled = False
        self._running = False
        self._thread: threading.Thread | None = None
        self._last_probe = 0.0

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.config.watchdog_stall_multiple <= 0:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="cs-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False

    # --- detection ---------------------------------------------------------

    def peer_max_height(self) -> int:
        """Best height any connected peer reports, from consensus round
        gossip and fast-sync status responses."""
        best = 0
        states = getattr(self.consensus_reactor, "_peer_states", {})
        for ps in list(states.values()):
            best = max(best, ps.prs.height)
        pool = getattr(self.bc_reactor, "pool", None)
        if pool is not None:
            best = max(best, pool.max_peer_height())
        return best

    def probe_peer_heights(self) -> None:
        """Actively solicit heights: nobody broadcasts StatusRequest
        outside fast sync, so a stalled node's pool view of its peers goes
        stale exactly when it matters. The responses land in the pool via
        the blockchain reactor's always-on receive path."""
        sw = getattr(self.bc_reactor, "switch", None)
        if sw is None:
            return
        from tendermint_tpu.blockchain.reactor import (
            BLOCKCHAIN_CHANNEL,
            msg_status_request,
        )

        sw.broadcast(BLOCKCHAIN_CHANNEL, msg_status_request())

    def _set_stalled(self, stalled: bool) -> None:
        if stalled == self.stalled:
            return
        self.stalled = stalled
        if self.metrics is not None:
            self.metrics.consensus_stalled.set(1.0 if stalled else 0.0)

    def _loop(self) -> None:
        last_h = self.block_store.height
        last_t = time.monotonic()
        while self._running:
            time.sleep(self.check_interval_s)
            try:
                h = self.block_store.height
                now = time.monotonic()
                if h > last_h or self.consensus_reactor.wait_sync:
                    # progress, or a sync (ours or state sync) already owns
                    # recovery -- restart the stall clock either way
                    last_h, last_t = h, now
                    self._set_stalled(False)
                    continue
                if now - last_t < self.config.watchdog_stall_s():
                    continue
                self._set_stalled(True)
                lead = self.peer_max_height() - h
                if lead < self.config.watchdog_peer_lead:
                    # stalled but nobody provably ahead: hold position and
                    # ask the peers for their heights directly (rate-limited
                    # — a long partition must not turn the check cadence
                    # into a broadcast storm)
                    if now - self._last_probe >= 1.0:
                        self._last_probe = now
                        self.probe_peer_heights()
                    continue
                self.recoveries += 1
                if self.metrics is not None:
                    self.metrics.watchdog_recoveries.add(1)
                if self.logger is not None:
                    self.logger.info("watchdog: consensus stalled, handing "
                                     "back to fast sync",
                                     height=h, peer_lead=lead)
                self.recover_fn()
                last_h, last_t = self.block_store.height, time.monotonic()
                self._set_stalled(False)
            except Exception as e:  # noqa: BLE001 - the watchdog must never
                # kill a node; a failed recovery retries after the next
                # full stall window
                if self.logger is not None:
                    self.logger.error("watchdog recovery failed", err=e)
                last_t = time.monotonic()
