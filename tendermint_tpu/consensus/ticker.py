"""TimeoutTicker: schedules round timeouts, newer schedules overwrite older
(reference: consensus/ticker.go:17,31-134).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int  # RoundStepType

    def __str__(self) -> str:
        return f"{self.duration_s} ; {self.height}/{self.round} {self.step}"


class TimeoutTicker:
    """Fires `callback(TimeoutInfo)` after ti.duration_s, unless overwritten.

    Mirrors timeoutRoutine semantics: scheduling a new timeout stops the
    pending one; stale timeouts (older height/round/step) are ignored at
    schedule time (reference: consensus/ticker.go:100-134)."""

    def __init__(self, callback, clock=None):
        self._callback = callback
        # per-node time source (utils/clock.py): the clock's rate scales
        # every scheduled duration, so a skew-rate nemesis can make one
        # node's round timeouts run fast or slow relative to the mesh
        self._clock = clock
        self._timer: threading.Timer | None = None
        self._current: TimeoutInfo | None = None
        self._mtx = threading.Lock()
        self._stopped = False

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped:
                return
            cur = self._current
            if cur is not None:
                # ignore timeouts for an older h/r/s than the pending one
                if (ti.height, ti.round, ti.step) < (cur.height, cur.round, cur.step):
                    return
            if self._timer is not None:
                self._timer.cancel()
            self._current = ti
            delay = (ti.duration_s if self._clock is None
                     else self._clock.timer_duration(ti.duration_s))
            self._timer = threading.Timer(delay, self._fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._stopped or self._current is not ti:
                return
            self._current = None
            self._timer = None
        self._callback(ti)

    def stop(self) -> None:
        with self._mtx:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._current = None

    def resume(self) -> None:
        """Accept schedules again after stop() (the stall watchdog pauses
        consensus for a fast-sync catchup, then restarts it)."""
        with self._mtx:
            self._stopped = False
