"""Byzantine misbehavior hooks for adversarial testing (reference:
test/maverick/consensus/misbehavior.go:16).

Install on a ConsensusState via
`cs.misbehaviors["prevote"] = double_prevote(node.switch)` BEFORE starting
the node. These deliberately violate the protocol; honest peers must detect
the equivocation (DuplicateVoteEvidence) and keep committing as long as the
byzantine power stays below 1/3.
"""

from __future__ import annotations

from tendermint_tpu.consensus.reactor import VOTE_CHANNEL, msg_vote
from tendermint_tpu.consensus.state_machine import MsgInfo, VoteMessage
from tendermint_tpu.types.block_id import PartSetHeader
from tendermint_tpu.types.vote import PREVOTE_TYPE


def double_prevote(switch):
    """Hook factory: sign TWO conflicting prevotes (proposal block + nil)
    and push BOTH directly to every peer, exactly like the maverick's
    DoublePrevoteMisbehavior sends over the vote channel (reference:
    misbehavior.go:93-118).

    Requires a signer without a double-sign guard (MockPV); FilePV would
    refuse the second signature -- which is itself worth testing.
    """

    def hook(cs, height: int, round_: int) -> None:
        rs = cs.rs
        if rs.proposal_block is None:
            cs._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        vote_a = cs._sign_vote(PREVOTE_TYPE, rs.proposal_block.hash(),
                               rs.proposal_block_parts.header())
        vote_b = cs._sign_vote(PREVOTE_TYPE, b"", PartSetHeader())
        # Internally track only vote A (adding both would trip our own
        # conflict detection and panic the node -- byzantine, not suicidal).
        if vote_a is not None:
            cs._internal_queue.put(MsgInfo(VoteMessage(vote_a), ""))
        # Gossip only ever serves votes from our own vote set, so the
        # equivocating pair must be PUSHED to peers over the wire.
        with switch._peers_mtx:
            peers = list(switch.peers.values())
        for v in (vote_a, vote_b):
            if v is None:
                continue
            for p in peers:
                p.try_send(VOTE_CHANNEL, msg_vote(v))

    return hook


def absent_prevote(cs, height: int, round_: int) -> None:
    """Never prevote (a silent validator)."""
