"""Byzantine misbehavior suite for adversarial testing (reference:
test/maverick/consensus/misbehavior.go:16 — the maverick node's pluggable
misbehavior table, grown here into a behavior catalog with per-height
scheduling; docs/BYZANTINE.md is the cookbook).

Hook protocol: ``cs.misbehaviors[slot] = fn`` where slot is one of
``"prevote"``, ``"precommit"``, ``"propose"`` and ``fn(cs, height, round)``
returns truthy when it HANDLED the action (the state machine skips its
default behavior) and falsy to fall through to the honest default — which
is what lets :func:`scheduled` window a behavior to a height range while
the node plays honest everywhere else.

Install on a ConsensusState BEFORE starting the node, or at any point on a
live node via :func:`install` (the node-level entry: swaps a
double-sign-guarded FilePV for an unguarded MockPV with the same key,
parses a behavior spec, and wires every slot). These deliberately violate
the protocol; honest peers must detect what is detectable
(DuplicateVoteEvidence for double votes, LightClientAttackEvidence for the
lunatic's fabricated headers) and keep committing as long as the byzantine
power stays below 1/3.

Behavior catalog (spec grammar ``<behavior>[~<lo>[-<hi>]]``, ``+``-joined
for per-height behavior maps, e.g. ``"equivocate~3-5+lunatic~7-"``):

* ``double_prevote``    — two conflicting prevotes (block + nil) pushed to
  every peer; the equivocation every honest node turns into
  DuplicateVoteEvidence.
* ``double_precommit``  — the precommit twin: two conflicting precommits
  at the same H/R.
* ``amnesia``           — "forgets" its POL lock: prevotes AND precommits
  the current round's proposal even when locked on a different block from
  an earlier round. No same-HRS double sign, so no DuplicateVoteEvidence —
  the attribute-nobody case of light-attack classification
  (types/evidence.py get_byzantine_validators).
* ``equivocate``        — equivocating proposer: signs TWO conflicting
  proposals for the same H/R and pushes each (proposal + full part set) to
  a disjoint half of its peers, splitting the prevote.
* ``lunatic``           — lunatic proposer: proposes blocks carrying a
  fabricated app hash on the live chain (honest validators reject and the
  round advances), and for every committed height in its window signs a
  fabricated header (bogus app/validators hashes under a claimed
  validator set it fully controls) served to light clients through the
  node's ``light_block`` RPC route — the staged light-client attack
  (docs/BYZANTINE.md cookbook; reference: light/detector.go's lunatic
  taxonomy).
* ``absent`` / ``absent_prevote`` — a silent validator.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from tendermint_tpu.consensus.reactor import (
    DATA_CHANNEL,
    VOTE_CHANNEL,
    msg_block_part,
    msg_proposal,
    msg_vote,
)
from tendermint_tpu.consensus.state_machine import (
    BlockPartMessage,
    MsgInfo,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE

FABRICATED_APP_HASH = b"\xba\xad\xf0\x0d" * 8


def _peers(switch) -> list:
    with switch._peers_mtx:
        return sorted(switch.peers.values(), key=lambda p: p.id)


def _push_votes(switch, votes) -> None:
    for p in _peers(switch):
        for v in votes:
            if v is not None:
                p.try_send(VOTE_CHANNEL, msg_vote(v))


def double_prevote(switch):
    """Hook factory: sign TWO conflicting prevotes (proposal block + nil)
    and push BOTH directly to every peer, exactly like the maverick's
    DoublePrevoteMisbehavior sends over the vote channel (reference:
    misbehavior.go:93-118).

    Requires a signer without a double-sign guard (MockPV); FilePV would
    refuse the second signature -- which is itself worth testing
    (tests/test_byzantine.py test_filepv_refuses_equivocating_signature).
    """

    def hook(cs, height: int, round_: int) -> bool:
        rs = cs.rs
        if rs.proposal_block is None:
            cs._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return True
        vote_a = cs._sign_vote(PREVOTE_TYPE, rs.proposal_block.hash(),
                               rs.proposal_block_parts.header())
        vote_b = cs._sign_vote(PREVOTE_TYPE, b"", PartSetHeader())
        # Internally track only vote A (adding both would trip our own
        # conflict detection and panic the node -- byzantine, not suicidal).
        if vote_a is not None:
            cs._internal_queue.put(MsgInfo(VoteMessage(vote_a), ""))
        # Gossip only ever serves votes from our own vote set, so the
        # equivocating pair must be PUSHED to peers over the wire.
        _push_votes(switch, (vote_a, vote_b))
        return True

    return hook


def double_precommit(switch):
    """The precommit twin of :func:`double_prevote`: two conflicting
    precommits (proposal block + nil) at the same H/R, both pushed to every
    peer. Honest vote sets raise ErrVoteConflictingVotes and the pair lands
    in the evidence pool as DuplicateVoteEvidence."""

    def hook(cs, height: int, round_: int) -> bool:
        rs = cs.rs
        if rs.proposal_block is None:
            cs._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
            return True
        vote_a = cs._sign_vote(PRECOMMIT_TYPE, rs.proposal_block.hash(),
                               rs.proposal_block_parts.header())
        vote_b = cs._sign_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
        if vote_a is not None:
            cs._internal_queue.put(MsgInfo(VoteMessage(vote_a), ""))
        _push_votes(switch, (vote_a, vote_b))
        return True

    return hook


def absent_prevote(cs, height: int, round_: int) -> bool:
    """Never prevote (a silent validator)."""
    return True


def amnesia_prevote(cs, height: int, round_: int) -> bool:
    """Forget the POL lock: prevote the CURRENT proposal block even when
    locked on a different one from an earlier round (the maverick's
    amnesia — prevote one block in round r, precommit another in r' > r;
    no same-HRS double sign, so evidence attribution comes up empty)."""
    rs = cs.rs
    if rs.proposal_block is None:
        cs._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
    else:
        cs._sign_add_vote(PREVOTE_TYPE, rs.proposal_block.hash(),
                          rs.proposal_block_parts.header())
    return True


def amnesia_precommit(cs, height: int, round_: int) -> bool:
    """The amnesiac's precommit: commit to the current round's proposal
    regardless of any earlier lock (and without requiring a polka)."""
    rs = cs.rs
    if rs.proposal_block is None:
        cs._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
    else:
        cs._sign_add_vote(PRECOMMIT_TYPE, rs.proposal_block.hash(),
                          rs.proposal_block_parts.header())
    return True


def equivocating_proposer(switch):
    """Propose-slot hook: when this node is the proposer, sign TWO
    conflicting proposals for the same H/R (same txs, nudged header time →
    different block hash) and push each proposal with its FULL part set to
    a disjoint half of the peers, splitting the honest prevote (reference:
    the maverick's double-proposal misbehaviors). Internally the node
    tracks variant A only."""

    def hook(cs, height: int, round_: int) -> bool:
        created = cs._create_proposal_block()
        if created is None:
            return True
        block_a, parts_a = created
        block_a.hash()  # fills the derived header hashes before the copy
        header_b = dataclasses.replace(
            block_a.header, time=block_a.header.time.add_ns(1_000_000))
        block_b = dataclasses.replace(block_a, header=header_b)
        parts_b = PartSet.from_data(block_b.marshal())

        proposals = []
        for block, parts in ((block_a, parts_a), (block_b, parts_b)):
            bid = BlockID(hash=block.hash(), part_set_header=parts.header())
            prop = Proposal(height=height, round=round_,
                            pol_round=cs.rs.valid_round, block_id=bid,
                            timestamp=Time.now())
            try:
                cs.priv_validator.sign_proposal(cs.state.chain_id, prop)
            except Exception:  # noqa: BLE001 - a guarded signer refuses the
                # second proposal; the equivocation simply degrades
                return True
            proposals.append((prop, parts))

        # track variant A ourselves (normal internal self-delivery)
        prop_a, _ = proposals[0]
        cs._internal_queue.put(MsgInfo(ProposalMessage(prop_a), ""))
        for i in range(parts_a.header().total):
            cs._internal_queue.put(
                MsgInfo(BlockPartMessage(height, round_, parts_a.get_part(i)), ""))

        peers = _peers(switch)
        halves = (peers[0::2], peers[1::2])
        for (prop, parts), half in zip(proposals, halves):
            for p in half:
                p.try_send(DATA_CHANNEL, msg_proposal(prop))
                for i in range(parts.header().total):
                    p.try_send(DATA_CHANNEL,
                               msg_block_part(height, round_, parts.get_part(i)))
        return True

    return hook


# --- lunatic: fabricated headers staged for light clients --------------------


def fabricate_light_block(node, height: int, claimed_power: int = 10):
    """Forge the lunatic's conflicting light block for a committed height:
    the real header with fabricated app/validators hashes under a claimed
    one-member validator set the byzantine node fully controls, and a
    commit carrying the node's own (real, attributable) signature — the
    posterior-corruption artifact a light client whose trusted common
    ancestor gave this key >= 1/3 power will accept from a byzantine
    primary (docs/BYZANTINE.md cookbook; reference: types/evidence.go:219
    ConflictingHeaderIsInvalid's lunatic taxonomy)."""
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.light_block import LightBlock, SignedHeader
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet
    from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, Vote

    meta = node.block_store.load_block_meta(height)
    if meta is None:
        return None
    pub = node.priv_validator.get_pub_key()
    claimed = ValidatorSet([Validator.new(pub, claimed_power)])
    fake_header = dataclasses.replace(
        meta.header,
        app_hash=FABRICATED_APP_HASH,
        validators_hash=claimed.hash(),
        next_validators_hash=claimed.hash(),
    )
    bid = BlockID(hash=fake_header.hash(),
                  part_set_header=PartSet.from_data(fake_header.marshal()).header())
    vote = Vote(type=PRECOMMIT_TYPE, height=height, round=0, block_id=bid,
                timestamp=fake_header.time.add_ns(1_000_000),
                validator_address=pub.address(), validator_index=0)
    node.priv_validator.sign_vote(node.genesis.chain_id, vote)
    commit = Commit(height=height, round=0, block_id=bid,
                    signatures=[CommitSig(BLOCK_ID_FLAG_COMMIT, pub.address(),
                                          vote.timestamp, vote.signature)])
    return LightBlock(signed_header=SignedHeader(fake_header, commit),
                      validator_set=claimed)


def lunatic_proposer(node, lo: int = 0, hi: int = 0):
    """Install the lunatic on ``node``: returns the propose-slot hook
    (fabricated-app-hash proposals honest validators reject) and wires the
    light-client attack staging — every committed height inside
    [lo, hi] (0 = open) gets a fabricated conflicting light block
    registered in ``node.byzantine_light_blocks``, which the node's
    ``light_block`` RPC route serves INSTEAD of the honest block (the
    byzantine-primary seam the live attack scenario drives)."""
    fakes = getattr(node, "byzantine_light_blocks", None)
    if fakes is None:
        fakes = node.byzantine_light_blocks = {}

    def in_window(h: int) -> bool:
        return h >= 1 and (lo <= 0 or h >= lo) and (hi <= 0 or h <= hi)

    def fabricate(h: int) -> None:
        if h in fakes or not in_window(h):
            return
        try:
            lb = fabricate_light_block(node, h)
        except Exception:  # noqa: BLE001 - fabrication must never crash the
            # consensus thread it piggybacks on (fail to lie, stay live)
            lb = None
        if lb is not None:
            fakes[h] = lb

    # posterior corruption: heights already committed when the node turns
    # byzantine are forged immediately (the key signed them honestly once;
    # now it signs a conflicting history for them)
    for h in range(max(node.block_store.base, 1), node.block_store.height + 1):
        fabricate(h)

    def on_step(rs) -> None:
        fabricate(rs.height - 1)

    node.consensus.on_new_round_step.append(on_step)
    # registered so a later install() (behavior cycling) can unhook the
    # fabricator: a node cycled away from lunatic must STOP forging
    if not hasattr(node, "_byz_on_step"):
        node._byz_on_step = []
    node._byz_on_step.append(on_step)

    def hook(cs, height: int, round_: int) -> bool:
        created = cs._create_proposal_block()
        if created is None:
            return True
        block, _ = created
        block.hash()
        lunatic_header = dataclasses.replace(block.header,
                                             app_hash=FABRICATED_APP_HASH)
        lunatic_block = dataclasses.replace(block, header=lunatic_header)
        parts = PartSet.from_data(lunatic_block.marshal())
        bid = BlockID(hash=lunatic_block.hash(), part_set_header=parts.header())
        prop = Proposal(height=height, round=round_, pol_round=cs.rs.valid_round,
                        block_id=bid, timestamp=Time.now())
        try:
            cs.priv_validator.sign_proposal(cs.state.chain_id, prop)
        except Exception:  # noqa: BLE001 - guarded signer: skip proposing
            return True
        msgs = [MsgInfo(ProposalMessage(prop), "")]
        for i in range(parts.header().total):
            msgs.append(MsgInfo(BlockPartMessage(height, round_,
                                                 parts.get_part(i)), ""))
        for m in msgs:
            cs._internal_queue.put(m)
            if cs.broadcast is not None:
                cs.broadcast(m.msg)
        return True

    return hook


# --- per-height behavior maps (spec grammar + installer) ---------------------

# behavior name -> (slots it occupies, factory(node, lo, hi) -> hook)
_SLOT_PREVOTE = "prevote"
_SLOT_PRECOMMIT = "precommit"
_SLOT_PROPOSE = "propose"

BEHAVIORS = ("double_prevote", "double_precommit", "amnesia", "equivocate",
             "lunatic", "absent", "absent_prevote")


@dataclass(frozen=True)
class BehaviorWindow:
    """One ``<behavior>[~<lo>[-<hi>]]`` segment; lo/hi of 0 mean open."""

    behavior: str
    lo: int = 0
    hi: int = 0

    def active(self, height: int) -> bool:
        return ((self.lo <= 0 or height >= self.lo)
                and (self.hi <= 0 or height <= self.hi))

    def describe(self) -> str:
        if self.lo <= 0 and self.hi <= 0:
            return self.behavior
        if self.lo == self.hi:
            return f"{self.behavior}~{self.lo}"
        return (f"{self.behavior}~{self.lo if self.lo > 0 else ''}"
                f"-{self.hi if self.hi > 0 else ''}")


def parse_spec(spec: str) -> list[BehaviorWindow]:
    """``"equivocate~3-5+lunatic~7-"`` -> behavior windows. A bare height
    (``~4``) pins one height; an open bound (``~3-``) runs to the end."""
    out = []
    for seg in spec.split("+"):
        seg = seg.strip()
        if not seg:
            continue
        name, _, hrange = seg.partition("~")
        if name not in BEHAVIORS:
            raise ValueError(f"unknown byzantine behavior {name!r} "
                             f"(want one of {', '.join(BEHAVIORS)})")
        lo = hi = 0
        if hrange:
            lo_s, dash, hi_s = hrange.partition("-")
            lo = int(lo_s) if lo_s else 0
            # bare `~h` pins one height; `~lo-` leaves the end open
            hi = int(hi_s) if hi_s else (lo if not dash else 0)
        out.append(BehaviorWindow(name, lo, hi))
    if not out:
        raise ValueError(f"empty byzantine spec {spec!r}")
    return out


def describe_spec(windows: list[BehaviorWindow]) -> str:
    return "+".join(w.describe() for w in windows)


def _hooks_for(node, w: BehaviorWindow) -> dict:
    """Slot -> hook for one window (hooks constructed once at install)."""
    sw = node.switch
    if w.behavior == "double_prevote":
        return {_SLOT_PREVOTE: double_prevote(sw)}
    if w.behavior == "double_precommit":
        return {_SLOT_PRECOMMIT: double_precommit(sw)}
    if w.behavior == "amnesia":
        return {_SLOT_PREVOTE: amnesia_prevote,
                _SLOT_PRECOMMIT: amnesia_precommit}
    if w.behavior == "equivocate":
        return {_SLOT_PROPOSE: equivocating_proposer(sw)}
    if w.behavior == "lunatic":
        return {_SLOT_PROPOSE: lunatic_proposer(node, w.lo, w.hi)}
    # absent / absent_prevote
    return {_SLOT_PREVOTE: absent_prevote}


def install(node, spec: str) -> list[BehaviorWindow]:
    """Make ``node`` byzantine per ``spec`` (maverick mode). Swaps a
    double-sign-guarded FilePV for an unguarded MockPV with the SAME key —
    a byzantine actor ignores its own safety guard — then wires per-slot
    dispatchers that consult the height windows, falling through to the
    honest default outside them. Installing again REPLACES the previous
    behavior map (the soak's ``byz`` action cycles behaviors this way)."""
    from tendermint_tpu.privval.file_pv import FilePV, MockPV

    windows = parse_spec(spec)
    if isinstance(node.priv_validator, FilePV):
        unguarded = MockPV(node.priv_validator.priv_key)
        node.priv_validator = unguarded
        node.consensus.priv_validator = unguarded
        node.consensus.priv_validator_pub_key = unguarded.get_pub_key()

    # unhook the previous map's side channels (the lunatic's light-block
    # fabricator rides on_new_round_step): replace means replace
    for cb in getattr(node, "_byz_on_step", ()):
        try:
            node.consensus.on_new_round_step.remove(cb)
        except ValueError:
            pass
    node._byz_on_step = []

    by_slot: dict[str, list] = {}
    for w in windows:
        for slot, hook in _hooks_for(node, w).items():
            by_slot.setdefault(slot, []).append((w, hook))

    def dispatcher(entries):
        def dispatch(cs, height: int, round_: int):
            for w, hook in entries:
                if w.active(height):
                    return hook(cs, height, round_)
            return False  # honest default outside every window

        return dispatch

    # replace, don't merge: a behavior-cycling schedule installs each new
    # map over the last (stale slots from the previous map must not linger)
    for slot in (_SLOT_PREVOTE, _SLOT_PRECOMMIT, _SLOT_PROPOSE):
        node.consensus.misbehaviors.pop(slot, None)
    for slot, entries in by_slot.items():
        node.consensus.misbehaviors[slot] = dispatcher(entries)
    return windows


__all__ = [
    "BEHAVIORS",
    "BehaviorWindow",
    "absent_prevote",
    "amnesia_precommit",
    "amnesia_prevote",
    "describe_spec",
    "double_precommit",
    "double_prevote",
    "equivocating_proposer",
    "fabricate_light_block",
    "install",
    "lunatic_proposer",
    "parse_spec",
]
