"""The Tendermint BFT consensus state machine.

A single consumer thread serializes every input (peer messages, own messages,
timeouts) exactly like the reference's receiveRoutine (reference:
consensus/state.go:707-790); all enter* transitions run on that thread. The
round step grammar, POL locking/unlocking rules, and WAL write points follow
consensus/state.go line-by-line semantics (citations inline), re-derived
against spec/consensus/consensus.md.

Differences from the reference are TPU-era, not semantic:
 - vote verification inside VoteSet can run through the batched TPU verifier;
 - goroutine fans are replaced by one input queue + a timer thread.
"""

from __future__ import annotations

import queue
import threading
import time as _time
from dataclasses import dataclass

from tendermint_tpu.consensus import cstypes
from tendermint_tpu.consensus.cstypes import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
    HeightVoteSet,
)
from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, WALMessageBlob
from tendermint_tpu.config.config import ConsensusConfig
from tendermint_tpu.encoding import proto as proto_enc
from tendermint_tpu.types import events as tmevents
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import Part, PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import (
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    Vote,
)
from tendermint_tpu.types.vote_set import VoteSet
from tendermint_tpu.utils import clock as tmclock
from tendermint_tpu.utils import peerscore
from tendermint_tpu.utils import trace as _trace


class ConsensusError(Exception):
    pass


class ErrInvalidProposalPOLRound(ConsensusError):
    pass


class ErrInvalidProposalSignature(ConsensusError):
    pass


class ErrAddingVote(ConsensusError):
    pass


# --- message types (reference: consensus/msgs.go) ---------------------------


@dataclass
class ProposalMessage:
    proposal: Proposal

    def wal_blob(self) -> WALMessageBlob:
        return WALMessageBlob("proposal", self.proposal.marshal())


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part

    def wal_blob(self) -> WALMessageBlob:
        body = (
            proto_enc.Writer()
            .varint(1, self.height)
            .varint(2, self.round)
            .message(3, self.part.marshal(), always=True)
            .out()
        )
        return WALMessageBlob("block_part", body)


@dataclass
class VoteMessage:
    vote: Vote

    def wal_blob(self) -> WALMessageBlob:
        return WALMessageBlob("vote", self.vote.marshal())


def wal_blob_to_msg(blob: WALMessageBlob):
    if blob.kind == "proposal":
        return ProposalMessage(Proposal.unmarshal(blob.payload))
    if blob.kind == "block_part":
        f = proto_enc.fields(blob.payload)
        return BlockPartMessage(
            height=proto_enc.as_sint64(f.get(1, [0])[-1]),
            round=proto_enc.as_sint64(f.get(2, [0])[-1]),
            part=Part.unmarshal(f.get(3, [b""])[-1]),
        )
    if blob.kind == "vote":
        return VoteMessage(Vote.unmarshal(blob.payload))
    if blob.kind == "timeout":
        f = proto_enc.fields(blob.payload)
        return TimeoutInfo(
            duration_s=proto_enc.as_sint64(f.get(1, [0])[-1]) / 1e9,
            height=proto_enc.as_sint64(f.get(2, [0])[-1]),
            round=proto_enc.as_sint64(f.get(3, [0])[-1]),
            step=proto_enc.as_sint64(f.get(4, [0])[-1]),
        )
    return None


def timeout_wal_blob(ti: TimeoutInfo) -> WALMessageBlob:
    body = (
        proto_enc.Writer()
        .varint(1, int(ti.duration_s * 1e9))
        .varint(2, ti.height)
        .varint(3, ti.round)
        .varint(4, ti.step)
        .out()
    )
    return WALMessageBlob("timeout", body)


@dataclass
class MsgInfo:
    msg: object
    peer_id: str = ""


def commit_to_vote_set(chain_id: str, commit: Commit, vals: ValidatorSet) -> VoteSet:
    """reference: types/vote_set.go CommitToVoteSet (via types/block.go)."""
    vote_set = VoteSet(chain_id, commit.height, commit.round, PRECOMMIT_TYPE, vals)
    for idx, cs_sig in enumerate(commit.signatures):
        if cs_sig.absent():
            continue
        added = vote_set.add_vote(commit.get_vote(idx))
        if not added:
            raise ConsensusError("failed to reconstruct LastCommit: duplicate vote")
    return vote_set


class ConsensusState:
    """reference: consensus/state.go:149 State."""

    def __init__(self, config: ConsensusConfig, state, block_exec, block_store,
                 mempool=None, evidence_pool=None, priv_validator=None,
                 event_bus=None, wal: WAL | None = None, logger=None,
                 clock=None):
        self.config = config
        # per-node time source (utils/clock.py, docs/NEMESIS.md): every
        # wall-clock read consensus makes — proposal/vote/commit timestamps,
        # round-0 scheduling, WAL frame times — goes through this clock so
        # a chaos harness can skew one fabric node without touching the host
        self.clock = clock if clock is not None else tmclock.DEFAULT
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool
        self.evpool = evidence_pool
        self.priv_validator = priv_validator
        self.priv_validator_pub_key = (
            priv_validator.get_pub_key() if priv_validator else None
        )
        self.event_bus = event_bus if event_bus is not None else tmevents.EventBus()
        self.wal = wal
        self.logger = logger
        # Flight recorder (utils/trace.py): node wiring swaps in the node's
        # instance tracer so a 50-node in-process mesh never interleaves
        # spans; a standalone machine records into the process default.
        self.tracer = _trace.DEFAULT

        self.rs = cstypes.RoundState()
        self.state = None  # sm.State; set by update_to_state

        # Peer gossip enters through a priority shed queue (docs/OVERLOAD.md):
        # at capacity, stale-height gossip sheds first and live-height votes
        # survive, and gossip threads NEVER block on a saturated consensus
        # consumer. Internal messages (own votes/proposals) keep a plain
        # bounded queue — they are never shed.
        self._msg_queue = peerscore.ShedQueue(maxsize=1000,
                                              on_shed=self._count_shed)
        self._internal_queue: queue.Queue = queue.Queue(maxsize=1000)
        self._ticker = TimeoutTicker(self._on_timeout_fired, clock=self.clock)
        self._timeout_queue: queue.Queue = queue.Queue()
        self._mtx = threading.RLock()
        self._holdover: object | None = None  # non-vote msg dequeued mid-drain
        # In-flight batched vote flush: (msgs, queued, PendingVerify).  The
        # drain dispatches a batch and keeps consuming the queue while the
        # device verifies; the result is applied before ANY other state
        # transition (next batch, timeout, non-vote message) so side-effect
        # order stays exactly arrival order (VERDICT r4 item 1b).
        self._pending_flush: tuple | None = None
        self._thread: threading.Thread | None = None
        self._running = False
        self.replay_mode = False
        self._n_steps = 0
        # Peer misbehavior scoreboard (utils/peerscore.py), set by node
        # wiring to the switch's board: invalid-signature lanes out of the
        # batched vote-drain bitmap (and the serial VoteError path) are
        # attributed to the delivering peer. None = scoring disabled
        # (standalone/replay machines).
        self.scoreboard = None
        # Maverick-style misbehavior hooks for adversarial testing
        # (reference: test/maverick/consensus/misbehavior.go:16;
        # consensus/misbehavior.py is the behavior catalog). Keys
        # "prevote" / "precommit" / "propose" -> fn(cs, height, round);
        # a truthy return means the hook HANDLED the action (the default
        # behavior is skipped), falsy falls through to the honest default
        # so height-windowed behavior maps can play honest outside their
        # window. Production nodes never set this.
        self.misbehaviors: dict = {}
        # decided-block callback fans (reactor hooks; reference evsw usage)
        self.on_new_round_step = []  # callbacks(rs)
        self.on_vote = []  # callbacks(vote)
        self.on_valid_block = []  # callbacks(rs)
        # called with each internally-generated message (own proposal, parts,
        # votes) for the reactor / test harness to gossip to peers
        self.broadcast = None

        if state is not None:
            # reconstruct LastCommit when resuming mid-chain (reference:
            # consensus/state.go:540-570 reconstructLastCommit)
            if state.last_block_height > 0:
                from tendermint_tpu.store.envelope import CorruptedStoreError

                try:
                    seen = block_store.load_seen_commit(state.last_block_height)
                except CorruptedStoreError:
                    # quarantined + repair scheduled by the store hook; the
                    # canonical commit row (written with block h+1) carries
                    # the same +2/3, so resume from it when it survives
                    try:
                        seen = block_store.load_block_commit(
                            state.last_block_height)
                    except CorruptedStoreError:
                        seen = None  # both rows rotten: fail typed below
                if seen is None:
                    raise ConsensusError(
                        f"failed to reconstruct last commit; seen commit for height "
                        f"{state.last_block_height} not found"
                    )
                last_precommits = commit_to_vote_set(
                    state.chain_id, seen, state.last_validators
                )
                if not last_precommits.has_two_thirds_majority():
                    raise ConsensusError(
                        "failed to reconstruct last commit; does not have +2/3 maj"
                    )
                self.rs.last_commit = last_precommits
            self.update_to_state(state)

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """reference: consensus/state.go:299-420 OnStart + startRoutines."""
        self._ticker.resume()  # no-op unless pause() stopped it
        if self.wal is not None and self.state is not None:
            # Empty WAL gets a height-0 end marker so crash replay works for
            # the very first height (reference: consensus/wal.go OnStart).
            if next(iter(self.wal.iter_messages()), None) is None:
                self.wal.write_sync(EndHeightMessage(0), self.clock.now_ns())
            self._catchup_replay(self.rs.height)
        self._running = True
        if self._thread is not None and self._thread.is_alive():
            # a pause() timed out joining a blocked receive routine: it
            # re-reads _running when it unblocks and simply resumes —
            # adopting it keeps the one-drainer invariant
            self._schedule_round_0()
            return
        self._thread = threading.Thread(
            target=self._receive_routine, name="cs-receive", daemon=True
        )
        self._thread.start()
        self._schedule_round_0()

    def stop(self) -> None:
        self._running = False
        self._ticker.stop()
        self._msg_queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.wal is not None:
            self.wal.close()

    def pause(self) -> None:
        """Stop the receive routine and ticker WITHOUT closing the WAL, so
        a later start() resumes cleanly. This is the stall watchdog's
        hand-back: consensus pauses, fast sync pulls the missing blocks,
        and switch_to_consensus restarts this machine at the tip."""
        self._running = False
        self._ticker.stop()
        self._msg_queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
            if not self._thread.is_alive():
                self._thread = None
            # else: the routine is blocked past the join budget — KEEP the
            # handle so start() can adopt it instead of racing a second
            # drainer against it (two threads mutating rs would fork us)

    def rewind_for_catchup(self) -> None:
        """Drop in-height commit progress so a fast-sync catchup can
        update_to_state PAST this height. A node stalled mid-commit (2/3
        precommits seen but the block never arrived — the classic
        partition stall) holds commit_round > -1, which update_to_state
        treats as \"about to commit THIS height\" and refuses to skip;
        after the hand-back the pipeline applies the height from a peer's
        stored commit instead, so that claim is void."""
        with self._mtx:
            self.rs.commit_round = -1
            self.rs.triggered_timeout_precommit = False

    def wait_sync(self, timeout: float = 1.0) -> None:
        """Drain the queues (test helper): returns once queued work at call
        time has been handled."""
        done = threading.Event()
        self._msg_queue.put(("__sync__", done))
        done.wait(timeout)

    # --- external input (reference: consensus/state.go:430-520) ------------

    def _gossip_priority(self, height: int) -> int:
        """Shed class for a peer gossip message: live-height messages
        survive overload, stale-height gossip (re-derivable from stores
        and gossip re-delivery) sheds first. The unlocked rs.height read
        only biases shedding, never correctness."""
        rs_h = self.rs.height
        if height == rs_h:
            return peerscore.PRIO_LIVE
        if height > rs_h:
            return peerscore.PRIO_FUTURE
        return peerscore.PRIO_STALE

    def _count_shed(self, channel: str) -> None:
        board = self.scoreboard
        if board is not None:
            board.count_shed(channel)

    def add_vote(self, vote: Vote, peer_id: str = "") -> None:
        if peer_id == "":
            self._internal_queue.put(MsgInfo(VoteMessage(vote), peer_id))
        else:
            self._msg_queue.put(MsgInfo(VoteMessage(vote), peer_id),
                                priority=self._gossip_priority(vote.height),
                                channel="vote")

    def set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        if peer_id == "":
            self._internal_queue.put(MsgInfo(ProposalMessage(proposal), peer_id))
        else:
            self._msg_queue.put(MsgInfo(ProposalMessage(proposal), peer_id),
                                priority=self._gossip_priority(proposal.height),
                                channel="proposal")

    def add_proposal_block_part(self, height: int, round_: int, part: Part,
                                peer_id: str = "") -> None:
        if peer_id == "":
            self._internal_queue.put(
                MsgInfo(BlockPartMessage(height, round_, part), peer_id))
        else:
            self._msg_queue.put(
                MsgInfo(BlockPartMessage(height, round_, part), peer_id),
                priority=self._gossip_priority(height), channel="block_part")

    def handle_txs_available(self) -> None:
        self._msg_queue.put(("__txs_available__", None))

    # --- round state snapshot ---------------------------------------------

    def get_round_state(self) -> cstypes.RoundState:
        with self._mtx:
            import copy

            return copy.copy(self.rs)

    # --- the serialized event loop -----------------------------------------

    def _receive_routine(self) -> None:
        """Crash shield around the drain loop: a stray exception must not
        kill the one consensus drainer silently (with ``_running`` still
        True nothing would ever restart it). Fail-stop instead: log, mark
        the machine stopped, and let the stall watchdog hand the node to
        fast-sync catchup (consensus/watchdog.py), which restarts a fresh
        machine at the tip."""
        try:
            # every span recorded on the consensus thread — including the
            # crypto-layer verify phases dispatched from it — lands in THIS
            # node's tracer (thread-local activation, utils/trace.py)
            with self.tracer.activate():
                self._receive_loop()
        except Exception as e:  # noqa: BLE001 - fail-stop, never die silent
            if self.logger is not None:
                self.logger.error("consensus receive routine crashed; "
                                  "halting this machine for watchdog "
                                  "recovery", err=e)
            self._running = False

    def _receive_loop(self) -> None:
        """reference: consensus/state.go:707-790. Strict ordering: internal
        queue drains before the peer queue; timeouts interleave."""
        while self._running:
            mi = None
            try:
                mi = self._internal_queue.get_nowait()
                internal = True
            except queue.Empty:
                internal = False
            if mi is None:
                try:
                    ti = self._timeout_queue.get_nowait()
                except queue.Empty:
                    ti = None
                if ti is not None:
                    # timeout decisions read round state: apply any
                    # in-flight vote flush first
                    self._flush_pending_votes()
                    # WAL the timeout HERE, at dequeue time, so WAL order
                    # matches processing order (reference consensus/state.go
                    # writes it in receiveRoutine immediately before
                    # handleTimeout) — writing at fire time on the ticker
                    # thread could log it ahead of messages handled first.
                    if self.wal is not None and not self.replay_mode:
                        self.wal.write(timeout_wal_blob(ti), _time.time_ns())
                    self._do_handle_timeout(ti)
                    continue
                if self._holdover is not None:
                    mi, self._holdover = self._holdover, None
                else:
                    try:
                        mi = self._msg_queue.get_nowait()
                    except queue.Empty:
                        # idle: nothing left to overlap the in-flight flush
                        # with, resolve it now
                        self._flush_pending_votes()
                        try:
                            mi = self._msg_queue.get(timeout=0.02)
                        except queue.Empty:
                            continue
            if mi is None:
                self._flush_pending_votes()
                if not self._running:
                    return  # stop sentinel
                # stale wake-up sentinel from a previous pause()/stop():
                # a RESTARTED routine (watchdog hand-back) must not let it
                # silently kill the new thread
                continue
            if isinstance(mi, tuple):
                kind, payload = mi
                if kind == "__sync__":
                    self._flush_pending_votes()
                    if not self._internal_queue.empty() or not self._timeout_queue.empty():
                        self._msg_queue.put(mi)  # drain internals first
                    else:
                        payload.set()
                elif kind == "__txs_available__":
                    self._flush_pending_votes()
                    with self._mtx:
                        self._handle_txs_available()
                continue
            # Batched vote drain (the deferred batched addVote mode the
            # reference lacks; BASELINE config 5): when peer votes have piled
            # up, pull them all and verify their signatures in ONE
            # BatchVerifier flush instead of one scalar verify per vote.
            if (not internal and isinstance(mi.msg, VoteMessage)
                    and not self._msg_queue.empty()):
                votes = self._drain_votes(mi)
                if len(votes) > 1:
                    if self.wal is not None and not self.replay_mode:
                        for m in votes:
                            blob = m.msg.wal_blob()
                            blob.peer_id = m.peer_id
                            self.wal.write(blob, _time.time_ns())
                    tr = self.tracer
                    with self._mtx:
                        if tr.enabled:
                            # the drain span carries the height; verify
                            # phases dispatched inside inherit it
                            with tr.span("consensus.vote_drain",
                                         height=self.rs.height,
                                         round=self.rs.round,
                                         votes=len(votes)):
                                self._handle_vote_batch(votes)
                        else:
                            self._handle_vote_batch(votes)
                    continue
            # Any other message mutates state through _handle_msg: apply the
            # in-flight vote flush first so side effects stay arrival-order.
            self._flush_pending_votes()
            # WAL discipline (reference: state.go:753-780): internal messages
            # are fsync'd, peer messages buffered.
            if self.wal is not None and not self.replay_mode:
                blob = mi.msg.wal_blob()
                blob.peer_id = mi.peer_id
                if internal:
                    self.wal.write_sync(blob, _time.time_ns())
                else:
                    self.wal.write(blob, _time.time_ns())
            with self._mtx:
                self._handle_msg(mi)

    def _drain_votes(self, first: MsgInfo) -> list[MsgInfo]:
        """Pull immediately-available peer VoteMessages (bounded so internal
        messages and timeouts are not starved). A non-vote message ends the
        drain and is held over for the next loop iteration."""
        batch = [first]
        while len(batch) < 1024:
            try:
                nxt = self._msg_queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(nxt, MsgInfo) and isinstance(nxt.msg, VoteMessage):
                batch.append(nxt)
            else:
                self._holdover = nxt
                break
        return batch

    def _handle_vote_batch(self, msgs: list[MsgInfo]) -> None:
        """Verify the batch's signatures in one BatchVerifier flush, then
        apply each vote IN ARRIVAL ORDER through the normal addVote path with
        the signature check skipped. Per-vote side effects (conflict/evidence
        detection, maj23 bookkeeping, round transitions) are bit-identical to
        serial processing: the batch verifies exactly the triple
        (val_set[index].pub_key, sign_bytes(chain_id), signature) that
        VoteSet.add_vote would check (reference: types/vote_set.go:205).

        Device flushes are applied ASYNCHRONOUSLY: the dispatch is issued
        here, the drain keeps consuming the queue while the device + tunnel
        work, and the result is applied by _flush_pending_votes before any
        later state transition (r4 verdict item 1b: overlap the sync floor
        with consensus work). Verification inputs are state-independent --
        (pubkey, sign bytes, signature) fixed at dispatch -- and batch k is
        always applied before batch k+1, so observable ordering is exactly
        the serial drain's.

        A DEVICE-BOUND dispatch lands on the continuous-batching verify
        service (crypto/verify_service.py): this drain's flush coalesces
        with any concurrent fast-sync / range / fabric-peer dispatches into
        ONE shared kernel launch, so a drain racing other verify traffic
        pays one sync floor, not one each (sub-crossover host flushes keep
        verifying inline — they never pay a floor). has_device_output() on
        the returned handle sees through to an in-flight service request,
        so the stash-and-overlap path below engages exactly as with a raw
        device handle."""
        from tendermint_tpu.crypto import batch as crypto_batch
        from tendermint_tpu.crypto import sigcache

        # Apply the in-flight previous flush FIRST: if it commits and
        # advances the height, a snapshot taken before it would filter every
        # vote of this batch against the stale height and silently demote
        # the whole drain to serial verification exactly on the busiest
        # transition (ADVICE r5 item 3).
        self._flush_pending_votes(_locked=True)
        rs = self.rs
        val_set = rs.votes.val_set if rs.votes is not None else None
        height = rs.height
        dc = sigcache.DrainCache()
        try:
            verifier = crypto_batch.create_batch_verifier()
            queued: list[int] = []
            sb_memo: dict[tuple, bytes] = {}
            chain_id = self.state.chain_id
            for i, m in enumerate(msgs):
                v = m.msg.vote
                if val_set is None or v.height != height:
                    continue  # serial path handles late/early votes
                if not (0 <= v.validator_index < val_set.size()):
                    continue  # precheck will raise the right error serially
                addr, val = val_set.get_by_index(v.validator_index)
                if val is None or addr != v.validator_address:
                    continue
                sb_key = (v.height, v.round, v.type, v.block_id.key(),
                          v.timestamp)
                sb = sb_memo.get(sb_key)
                if sb is None:
                    sb = sb_memo[sb_key] = v.sign_bytes(chain_id)
                # Gossip re-delivers the same vote from several peers; a
                # known-verified triple skips straight to the serial
                # accept-replay (duplicate detection happens there).
                if dc.check(i, val.pub_key.bytes(), sb, v.signature):
                    continue
                verifier.add(val.pub_key, sb, v.signature)
                queued.append(i)
            if not queued:
                # commit with an empty flush: applies the cache hits and
                # flushes the batched hit/miss metrics deltas
                self._apply_vote_results(msgs, dc.commit([], []))
                return
            pending = verifier.dispatch()
            if pending.has_device_output():
                # stash; the drain loop applies it before the next state
                # transition, overlapping the round trip with more draining
                self._pending_flush = (msgs, queued, dc, pending)
                return
            ok_by_i = self._resolve_vote_flush(queued, dc, pending)
        except Exception as e:  # noqa: BLE001
            # A flush failure (device OOM, runtime hiccup) must not kill the
            # consensus thread; fall back to per-vote scalar verification.
            # Cache hits stay verified -- they never touched this flush --
            # and the empty commit caches nothing but still flushes the
            # batched hit/miss metric deltas (counters must stay honest
            # exactly when degradation makes operators read them).
            ok_by_i = dc.commit([], [])
            if self.logger is not None:
                self.logger.error("batched vote verify failed; falling back "
                                  "to serial", err=e)
        self._apply_vote_results(msgs, ok_by_i)

    @staticmethod
    def _resolve_vote_flush(queued, dc, pending):
        """Resolve a dispatched vote flush into {msg index: verified}.
        Positively verified triples enter the signature cache in
        DrainCache.commit -- only from a resolved bitmap, so a resolve that
        raises (propagated to the caller's serial fallback) can never
        poison the cache."""
        _, bitmap = pending.resolve()
        return dc.commit(queued, bitmap)

    def _flush_pending_votes(self, _locked: bool = False) -> None:
        """Fetch and apply the in-flight batched vote flush, if any.
        _locked=True when the caller already holds self._mtx."""
        pf = self._pending_flush
        if pf is None:
            return
        self._pending_flush = None
        msgs, queued, dc, pending = pf
        try:
            ok_by_i = self._resolve_vote_flush(queued, dc, pending)
        except Exception as e:  # noqa: BLE001 - same fallback as the sync path
            ok_by_i = dc.commit([], [])
            if self.logger is not None:
                self.logger.error("batched vote verify failed; falling back "
                                  "to serial", err=e)
        if _locked:
            self._apply_vote_results(msgs, ok_by_i)
        else:
            with self._mtx:
                self._apply_vote_results(msgs, ok_by_i)

    def _apply_vote_results(self, msgs: list[MsgInfo],
                            ok_by_i: dict[int, bool]) -> None:
        for i, m in enumerate(msgs):
            ok = ok_by_i.get(i)
            if ok is False:
                # Same terminal state as the serial path's VoteError: vote
                # dropped, error logged, consensus thread lives on — but
                # the lane's FAILED bit is attributed to the delivering
                # peer: MsgInfo.peer_id traveled the whole drain, so the
                # batched bitmap sanctions exactly like serial verification
                self._punish_peer(m.peer_id)
                if self.logger is not None:
                    self.logger.error(
                        "failed to process message", err="invalid signature",
                        peer=m.peer_id)
                continue
            try:
                self._try_add_vote(m.msg.vote, m.peer_id, verified=bool(ok))
            except Exception as e:  # noqa: BLE001 - mirror _handle_msg
                if isinstance(e, ErrVoteInvalidSignature):
                    self._punish_peer(m.peer_id)
                if self.logger is not None:
                    self.logger.error("failed to process message", err=e,
                                      peer=m.peer_id)

    def _punish_peer(self, peer_id: str,
                     offense: str = "invalid_signature") -> None:
        board = self.scoreboard
        if board is not None and peer_id:
            board.record(peer_id, offense)

    def _on_timeout_fired(self, ti: TimeoutInfo) -> None:
        # hop onto the consensus thread; WAL write happens at dequeue
        self._timeout_queue.put(ti)

    def _handle_msg(self, mi: MsgInfo) -> None:
        """reference: consensus/state.go:799-890."""
        msg, peer_id = mi.msg, mi.peer_id
        try:
            if isinstance(msg, ProposalMessage):
                self._set_proposal(msg.proposal)
            elif isinstance(msg, BlockPartMessage):
                added = self._add_proposal_block_part(msg)
                if added and self.rs.proposal_block_parts.is_complete():
                    self._handle_complete_proposal(msg.height)
            elif isinstance(msg, VoteMessage):
                self._try_add_vote(msg.vote, peer_id)
        except Exception as e:  # noqa: BLE001
            # The reference logs and continues (consensus/state.go:880-890):
            # a bad peer message (invalid sig, wrong index, unwanted round...)
            # must never kill the consensus thread.
            if isinstance(e, ErrVoteInvalidSignature):
                self._punish_peer(peer_id)  # serial twin of the drain bitmap
            if self.logger is not None:
                self.logger.error("failed to process message", err=e, peer=peer_id)

    def _do_handle_timeout(self, ti: TimeoutInfo) -> None:
        """reference: consensus/state.go:890-940 handleTimeout."""
        with self._mtx:
            rs = self.rs
            if (ti.height != rs.height or ti.round < rs.round
                    or (ti.round == rs.round and ti.step < rs.step)):
                return
            if ti.step == STEP_NEW_HEIGHT:
                self._enter_new_round(ti.height, 0)
            elif ti.step == STEP_NEW_ROUND:
                self._enter_propose(ti.height, 0)
            elif ti.step == STEP_PROPOSE:
                self.event_bus.publish_event_timeout_propose(self._round_state_event())
                self._enter_prevote(ti.height, ti.round)
            elif ti.step == STEP_PREVOTE_WAIT:
                self.event_bus.publish_event_timeout_wait(self._round_state_event())
                self._enter_precommit(ti.height, ti.round)
            elif ti.step == STEP_PRECOMMIT_WAIT:
                self.event_bus.publish_event_timeout_wait(self._round_state_event())
                self._enter_precommit(ti.height, ti.round)
                self._enter_new_round(ti.height, ti.round + 1)

    def _handle_txs_available(self) -> None:
        """reference: consensus/state.go:940-975."""
        if self.rs.round != 0:
            return
        if self.rs.step == STEP_NEW_HEIGHT:
            if self._need_proof_block(self.rs.height):
                return
            remain = max(self.rs.start_time.unix_ns() - self.clock.now_ns(), 0) / 1e9
            self._schedule_timeout(remain + 0.001, self.rs.height, 0, STEP_NEW_ROUND)
        elif self.rs.step == STEP_NEW_ROUND:
            self._enter_propose(self.rs.height, 0)

    # --- state update ------------------------------------------------------

    def update_to_state(self, state) -> None:
        """reference: consensus/state.go:573-700 updateToState."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise ConsensusError(
                f"updateToState() expected state height of {rs.height} but found "
                f"{state.last_block_height}"
            )
        if self.state is not None and not self.state.is_empty():
            if state.last_block_height <= self.state.last_block_height:
                self._new_step()
                return

        validators = state.validators
        if state.last_block_height == 0:
            rs.last_commit = None
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if not precommits.has_two_thirds_majority():
                raise ConsensusError("wanted to form a commit, but precommits didn't have 2/3+")
            rs.last_commit = precommits

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = STEP_NEW_HEIGHT
        now_ns = self.clock.now_ns()
        base_ns = rs.commit_time.unix_ns() if not rs.commit_time.is_zero() else now_ns
        rs.start_time = Time.from_unix_ns(base_ns + int(self.config.commit_time_s() * 1e9))
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self._new_step()

    def _new_step(self) -> None:
        if self.wal is not None and not self.replay_mode:
            self.wal.write(
                WALMessageBlob("round_state", b"%d/%d/%d" % (
                    self.rs.height, self.rs.round, self.rs.step)),
                self.clock.now_ns(),
            )
        self._n_steps += 1
        # step-duration tracing (no-op beyond the enabled attribute check +
        # timestamp bookkeeping; the timestamp/step update is unconditional
        # so a disable/enable cycle can't produce a span covering the gap)
        now = _time.monotonic()
        last = getattr(self, "_last_step_at", None)
        prev_step = getattr(self, "_last_step_name", None)
        self._last_step_at = now
        self._last_step_name = self.rs.step
        if self.tracer.enabled and last is not None and prev_step is not None:
            # the measured duration belongs to the step we LEFT; the name
            # (not the int) is the step_duration histogram's label
            self.tracer.record("consensus.step", now - last,
                               height=self.rs.height, round=self.rs.round,
                               step=cstypes.STEP_NAMES.get(prev_step,
                                                           str(prev_step)))
        self.event_bus.publish_event_new_round_step(self._round_state_event())
        for cb in self.on_new_round_step:
            cb(self.rs)

    def _round_state_event(self) -> tmevents.EventDataRoundState:
        return tmevents.EventDataRoundState(
            height=self.rs.height, round=self.rs.round, step=self.rs.step_name()
        )

    # --- timeout scheduling -------------------------------------------------

    def _schedule_timeout(self, duration_s: float, height: int, round_: int, step: int) -> None:
        self._ticker.schedule_timeout(TimeoutInfo(duration_s, height, round_, step))

    def _schedule_round_0(self) -> None:
        """reference: consensus/state.go:522-530."""
        sleep = max(self.rs.start_time.unix_ns() - self.clock.now_ns(), 0) / 1e9
        self._schedule_timeout(sleep, self.rs.height, 0, STEP_NEW_HEIGHT)

    # --- ENTER: transitions -------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:976-1037."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and rs.step != STEP_NEW_HEIGHT):
            return

        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)

        rs.round = round_
        rs.step = STEP_NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round for round-skipping
        rs.triggered_timeout_precommit = False

        proposer = validators.get_proposer()
        self.event_bus.publish_event_new_round(tmevents.EventDataNewRound(
            height=height, round=round_, step=rs.step_name(),
            proposer_address=proposer.address if proposer else b"",
        ))

        wait_for_txs = (self.config.wait_for_txs() and round_ == 0
                        and not self._need_proof_block(height))
        if wait_for_txs:
            if self.config.create_empty_blocks_interval_s > 0:
                self._schedule_timeout(self.config.create_empty_blocks_interval_s,
                                       height, round_, STEP_NEW_ROUND)
            if self.mempool is not None and self.mempool.size() > 0:
                self._enter_propose(height, round_)
        else:
            self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        """reference: consensus/state.go:1040-1053."""
        if height == self.state.initial_height:
            return True
        from tendermint_tpu.store.envelope import CorruptedStoreError

        try:
            last_meta = self.block_store.load_block_meta(height - 1)
        except CorruptedStoreError:
            return True  # quarantined + repair scheduled; propose a proof
            # block conservatively rather than kill the round routine
        if last_meta is None:
            raise ConsensusError(f"needProofBlock: last block meta for height {height-1} not found")
        return self.state.app_hash != last_meta.header.app_hash

    def _enter_propose(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1060-1122."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and STEP_PROPOSE <= rs.step):
            return
        try:
            self._schedule_timeout(self.config.propose(round_), height, round_, STEP_PROPOSE)
            if self.priv_validator is None or self.priv_validator_pub_key is None:
                return
            address = self.priv_validator_pub_key.address()
            if not rs.validators.has_address(address):
                return
            if rs.validators.get_proposer().address == address:
                self._decide_proposal(height, round_)
        finally:
            rs.round = round_
            rs.step = STEP_PROPOSE
            self._new_step()
            if self._is_proposal_complete():
                self._enter_prevote(height, rs.round)

    def _decide_proposal(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1124-1180 defaultDecideProposal."""
        mb = self.misbehaviors.get("propose")
        if mb is not None and mb(self, height, round_):
            return
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            created = self._create_proposal_block()
            if created is None:
                return
            block, block_parts = created
        if self.wal is not None:
            self.wal.flush_and_sync()
        prop_block_id = BlockID(hash=block.hash(), part_set_header=block_parts.header())
        proposal = Proposal(height=height, round=round_, pol_round=rs.valid_round,
                            block_id=prop_block_id,
                            timestamp=Time.from_unix_ns(self.clock.now_ns()))
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:  # noqa: BLE001 - failed signing is non-fatal
            # Non-fatal in BOTH modes (reference: state.go:1124-1180 logs
            # outside replay, stays silent inside it). In catchup replay
            # after a crash that lost WAL frames past the last signed step,
            # the double-sign guard refuses this HRS -- the node must skip
            # proposing and let the next round proceed, not die here.
            if not self.replay_mode and self.logger is not None:
                self.logger.error("error signing proposal", height=height,
                                  round=round_, err=e)
            return
        msgs = [MsgInfo(ProposalMessage(proposal), "")]
        for i in range(block_parts.header().total):
            part = block_parts.get_part(i)
            msgs.append(MsgInfo(BlockPartMessage(height, round_, part), ""))
        for m in msgs:
            self._internal_queue.put(m)
            if self.broadcast is not None:
                self.broadcast(m.msg)

    def _create_proposal_block(self):
        """reference: consensus/state.go:1189-1223."""
        rs = self.rs
        if rs.height == self.state.initial_height:
            commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            commit = rs.last_commit.make_commit()
        else:
            return None
        proposer_addr = self.priv_validator_pub_key.address()
        block = self.block_exec.create_proposal_block(
            rs.height, self.state, commit, proposer_addr
        )
        parts = PartSet.from_data(block.marshal())
        return block, parts

    def _is_proposal_complete(self) -> bool:
        """reference: consensus/state.go:1182-1196."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1226-1250."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and STEP_PREVOTE <= rs.step):
            return
        self._do_prevote(height, round_)
        rs.round = round_
        rs.step = STEP_PREVOTE
        self._new_step()

    def _do_prevote(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1252-1284 defaultDoPrevote."""
        mb = self.misbehaviors.get("prevote")
        if mb is not None and mb(self, height, round_):
            return
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(PREVOTE_TYPE, rs.locked_block.hash(),
                                rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception:  # noqa: BLE001 - invalid proposal -> prevote nil
            self._sign_add_vote(PREVOTE_TYPE, b"", PartSetHeader())
            return
        self._sign_add_vote(PREVOTE_TYPE, rs.proposal_block.hash(),
                            rs.proposal_block_parts.header())

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1286-1315."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and STEP_PREVOTE_WAIT <= rs.step):
            return
        if not rs.votes.prevotes(round_).has_two_thirds_any():
            raise ConsensusError(
                f"entering prevote wait step ({height}/{round_}), but prevotes "
                "does not have any +2/3 votes"
            )
        rs.round = round_
        rs.step = STEP_PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(self.config.prevote(round_), height, round_, STEP_PREVOTE_WAIT)

    def _enter_precommit(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1322-1417."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and STEP_PRECOMMIT <= rs.step):
            return
        self.tracer.mark("consensus.precommit", height=height, round=round_)

        def done():
            rs.round = round_
            rs.step = STEP_PRECOMMIT
            self._new_step()

        mb = self.misbehaviors.get("precommit")
        if mb is not None and mb(self, height, round_):
            done()
            return

        block_id, ok = rs.votes.prevotes(round_).two_thirds_majority()
        if not ok:
            # No polka: precommit nil.
            self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
            done()
            return

        self.event_bus.publish_event_polka(self._round_state_event())
        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise ConsensusError(f"this POLRound should be {round_} but got {pol_round}")

        if len(block_id.hash) == 0:
            # +2/3 prevoted nil: unlock and precommit nil.
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self.event_bus.publish_event_unlock(self._round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
            done()
            return

        if rs.locked_block is not None and rs.locked_block.hashes_to(block_id.hash):
            # relock
            rs.locked_round = round_
            self.event_bus.publish_event_relock(self._round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header)
            done()
            return

        if rs.proposal_block is not None and rs.proposal_block.hashes_to(block_id.hash):
            # lock the proposal block
            self.block_exec.validate_block(self.state, rs.proposal_block)
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self.event_bus.publish_event_lock(self._round_state_event())
            self._sign_add_vote(PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header)
            done()
            return

        # Polka for a block we don't have: unlock, fetch, precommit nil.
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.part_set_header):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
        self.event_bus.publish_event_unlock(self._round_state_event())
        self._sign_add_vote(PRECOMMIT_TYPE, b"", PartSetHeader())
        done()

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """reference: consensus/state.go:1419-1454."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
                rs.round == round_ and rs.triggered_timeout_precommit):
            return
        if not rs.votes.precommits(round_).has_two_thirds_any():
            raise ConsensusError(
                f"entering precommit wait step ({height}/{round_}), but precommits "
                "does not have any +2/3 votes"
            )
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(self.config.precommit(round_), height, round_,
                               STEP_PRECOMMIT_WAIT)

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """reference: consensus/state.go:1476-1537."""
        rs = self.rs
        if rs.height != height or STEP_COMMIT <= rs.step:
            return
        self.tracer.mark("consensus.commit", height=height,
                         round=commit_round)

        block_id, ok = rs.votes.precommits(commit_round).two_thirds_majority()
        if not ok:
            raise ConsensusError("RunActionCommit() expects +2/3 precommits")

        if rs.locked_block is not None and rs.locked_block.hashes_to(block_id.hash):
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts

        if rs.proposal_block is None or not rs.proposal_block.hashes_to(block_id.hash):
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    block_id.part_set_header):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
                self.event_bus.publish_event_valid_block(self._round_state_event())
                for cb in self.on_valid_block:
                    cb(self.rs)

        rs.step = STEP_COMMIT
        rs.commit_round = commit_round
        rs.commit_time = Time.from_unix_ns(self.clock.now_ns())
        self._new_step()
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        """reference: consensus/state.go:1539-1565."""
        rs = self.rs
        if rs.height != height:
            raise ConsensusError(f"tryFinalizeCommit() cs.Height: {rs.height} vs {height}")
        block_id, ok = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        if not ok or len(block_id.hash) == 0:
            return
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(block_id.hash):
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """reference: consensus/state.go:1567-1692."""
        rs = self.rs
        if rs.height != height or rs.step != STEP_COMMIT:
            return
        block_id, ok = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok:
            raise ConsensusError("cannot finalize commit; commit does not have 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise ConsensusError("expected ProposalBlockParts header to be commit header")
        if not block.hashes_to(block_id.hash):
            raise ConsensusError("cannot finalize commit; proposal block does not hash to commit hash")
        # commit→apply overlap (docs/EXECUTION.md): dispatch the block's
        # LastCommit verification on-device now so the round trip rides
        # under the structural checks; the resolved handle then makes
        # apply_block's re-validation free (resolve() is idempotent),
        # collapsing the path's two synchronous verifies into one async one.
        commit_pending = self.block_exec.dispatch_commit_verify(self.state, block)
        self.block_exec.validate_block(self.state, block,
                                       commit_pending=commit_pending)

        from tendermint_tpu.utils import faults

        # crash site 1 (reference: state.go:1605)
        faults.fail_point("consensus.finalize.save_block")
        if self.block_store.height < block.header.height:
            seen_commit = rs.votes.precommits(rs.commit_round).make_commit()
            with self.tracer.span("consensus.store_save", height=height):
                self.block_store.save_block(block, block_parts, seen_commit)

        # crash site 2 (reference: state.go:1619)
        faults.fail_point("consensus.finalize.end_height")
        if self.wal is not None:
            self.wal.write_sync(EndHeightMessage(height), self.clock.now_ns())

        # crash site 3 (reference: state.go:1642)
        faults.fail_point("consensus.finalize.apply_block")
        state_copy = self.state.copy()
        with self.tracer.span("consensus.abci_apply", height=height):
            state_copy, retain_height = self.block_exec.apply_block(
                state_copy,
                BlockID(hash=block.hash(), part_set_header=block_parts.header()),
                block,
                commit_pending=commit_pending,
            )

        # crash site 4 (reference: state.go:1667)
        faults.fail_point("consensus.finalize.prune")
        if retain_height > 0:
            try:
                self.block_store.prune_blocks(retain_height)
            except Exception:  # noqa: BLE001
                pass

        self.update_to_state(state_copy)

        # crash site 5 (reference: state.go:1685)
        faults.fail_point("consensus.finalize.done")
        if self.priv_validator is not None:
            self.priv_validator_pub_key = self.priv_validator.get_pub_key()
        self._schedule_round_0()

    # --- proposal handling --------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """reference: consensus/state.go:1809-1850 defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
                proposal.pol_round >= 0 and proposal.pol_round >= proposal.round):
            raise ErrInvalidProposalPOLRound()
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
                proposal.sign_bytes(self.state.chain_id), proposal.signature):
            raise ErrInvalidProposalSignature()
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(proposal.block_id.part_set_header)
        self.tracer.mark("consensus.proposal", height=proposal.height,
                         round=proposal.round)

    def _add_proposal_block_part(self, msg: BlockPartMessage) -> bool:
        """reference: consensus/state.go:1850-1920."""
        rs = self.rs
        if rs.height != msg.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except ValueError as e:
            raise ConsensusError(str(e)) from e
        if not added:
            return False
        if rs.proposal_block_parts.byte_size > self.state.consensus_params.block.max_bytes:
            raise ConsensusError("total size of proposal block parts exceeds maximum block bytes")
        if rs.proposal_block_parts.is_complete():
            rs.proposal_block = Block.unmarshal(rs.proposal_block_parts.assemble())
            self.tracer.mark("consensus.block_parts", height=rs.height,
                             round=rs.round,
                             parts=rs.proposal_block_parts.header().total)
            self.event_bus.publish_event_complete_proposal(
                tmevents.EventDataCompleteProposal(
                    height=rs.height, round=rs.round, step=rs.step_name(),
                    block_id=BlockID(hash=rs.proposal_block.hash(),
                                     part_set_header=rs.proposal_block_parts.header()),
                ))
        return True

    def _handle_complete_proposal(self, block_height: int) -> None:
        """reference: consensus/state.go:1920-1945."""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round)
        block_id, has_two_thirds = (prevotes.two_thirds_majority()
                                    if prevotes else (None, False))
        if has_two_thirds and not block_id.is_zero() and rs.valid_round < rs.round:
            if rs.proposal_block.hashes_to(block_id.hash):
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
        if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
            self._enter_prevote(block_height, rs.round)
            if has_two_thirds:
                self._enter_precommit(block_height, rs.round)
        elif rs.step == STEP_COMMIT:
            self._try_finalize_commit(block_height)

    # --- votes --------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str, verified: bool = False) -> bool:
        """reference: consensus/state.go:1947-1995."""
        try:
            return self._add_vote(vote, peer_id, verified=verified)
        except ErrVoteConflictingVotes as e:
            if self.priv_validator_pub_key is not None and (
                    vote.validator_address == self.priv_validator_pub_key.address()):
                raise  # conflicting vote from ourselves
            if self.evpool is not None:
                self.evpool.report_conflicting_votes(e.vote_a, e.vote_b)
            return getattr(e, "added", False)

    def _add_vote(self, vote: Vote, peer_id: str, verified: bool = False) -> bool:
        """reference: consensus/state.go:1995-2168."""
        rs = self.rs

        # Late precommit for the previous height while in NewHeight step.
        if vote.height + 1 == rs.height and vote.type == PRECOMMIT_TYPE:
            if rs.step != STEP_NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote, verified=verified)
            if not added:
                return False
            self.event_bus.publish_event_vote(tmevents.EventDataVote(vote=vote))
            for cb in self.on_vote:
                cb(vote)
            if self.config.skip_timeout_commit and rs.last_commit.has_all():
                self._enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            return False

        height = rs.height
        added = rs.votes.add_vote(vote, peer_id, verified=verified)
        if not added:
            return False
        self.event_bus.publish_event_vote(tmevents.EventDataVote(vote=vote))
        for cb in self.on_vote:
            cb(vote)

        if vote.type == PREVOTE_TYPE:
            prevotes = rs.votes.prevotes(vote.round)
            block_id, ok = prevotes.two_thirds_majority()
            if ok:
                # Unlock if cs.LockedRound < vote.Round <= cs.Round and the
                # POL is for something else (reference: state.go:2060-2083).
                if (rs.locked_block is not None
                        and rs.locked_round < vote.round <= rs.round
                        and not rs.locked_block.hashes_to(block_id.hash)):
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                    self.event_bus.publish_event_unlock(self._round_state_event())
                # Update Valid* (reference: state.go:2085-2113).
                if (len(block_id.hash) != 0 and rs.valid_round < vote.round
                        and vote.round == rs.round):
                    if rs.proposal_block is not None and rs.proposal_block.hashes_to(block_id.hash):
                        rs.valid_round = vote.round
                        rs.valid_block = rs.proposal_block
                        rs.valid_block_parts = rs.proposal_block_parts
                    else:
                        rs.proposal_block = None
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                            block_id.part_set_header):
                        rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
                    self.event_bus.publish_event_valid_block(self._round_state_event())
                    for cb in self.on_valid_block:
                        cb(rs)
            # Round transitions (reference: state.go:2115-2133).
            if rs.round < vote.round and prevotes.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
            elif rs.round == vote.round and STEP_PREVOTE <= rs.step:
                block_id, ok = prevotes.two_thirds_majority()
                if ok and (self._is_proposal_complete() or len(block_id.hash) == 0):
                    self._enter_precommit(height, vote.round)
                elif prevotes.has_two_thirds_any():
                    self._enter_prevote_wait(height, vote.round)
            elif (rs.proposal is not None and 0 <= rs.proposal.pol_round == vote.round
                  and self._is_proposal_complete()):
                self._enter_prevote(height, rs.round)

        elif vote.type == PRECOMMIT_TYPE:
            precommits = rs.votes.precommits(vote.round)
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                self._enter_new_round(height, vote.round)
                self._enter_precommit(height, vote.round)
                if len(block_id.hash) != 0:
                    self._enter_commit(height, vote.round)
                    if self.config.skip_timeout_commit and precommits.has_all():
                        self._enter_new_round(rs.height, 0)
                else:
                    self._enter_precommit_wait(height, vote.round)
            elif rs.round <= vote.round and precommits.has_two_thirds_any():
                self._enter_new_round(height, vote.round)
                self._enter_precommit_wait(height, vote.round)
        return added

    # --- signing ------------------------------------------------------------

    def _sign_vote(self, msg_type: int, hash_: bytes, header: PartSetHeader) -> Vote | None:
        """reference: consensus/state.go:2170-2215."""
        if self.wal is not None:
            self.wal.flush_and_sync()
        if self.priv_validator_pub_key is None:
            return None
        addr = self.priv_validator_pub_key.address()
        val_idx, _ = self.rs.validators.get_by_address(addr)
        vote = Vote(
            type=msg_type,
            height=self.rs.height,
            round=self.rs.round,
            block_id=BlockID(hash=hash_, part_set_header=header),
            timestamp=self._vote_time(),
            validator_address=addr,
            validator_index=val_idx,
        )
        self.priv_validator.sign_vote(self.state.chain_id, vote)
        return vote

    def _vote_time(self) -> Time:
        """BFT time monotonicity (reference: consensus/state.go:2216-2234)."""
        now = Time.from_unix_ns(self.clock.now_ns())
        min_vote_time = now
        time_iota_ns = self.state.consensus_params.block.time_iota_ms * 1_000_000
        if self.rs.locked_block is not None:
            min_vote_time = self.rs.locked_block.header.time.add_ns(time_iota_ns)
        elif self.rs.proposal_block is not None:
            min_vote_time = self.rs.proposal_block.header.time.add_ns(time_iota_ns)
        return now if now > min_vote_time else min_vote_time

    def _sign_add_vote(self, msg_type: int, hash_: bytes, header: PartSetHeader) -> Vote | None:
        """reference: consensus/state.go:2236-2263."""
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        if not self.rs.validators.has_address(self.priv_validator_pub_key.address()):
            return None
        try:
            vote = self._sign_vote(msg_type, hash_, header)
        except Exception:  # noqa: BLE001 - double-sign guard etc: don't vote
            return None
        if vote is not None:
            self._internal_queue.put(MsgInfo(VoteMessage(vote), ""))
            if self.broadcast is not None:
                self.broadcast(VoteMessage(vote))
        return vote

    # --- WAL catchup replay -------------------------------------------------

    def _catchup_replay(self, cs_height: int) -> None:
        """Replay WAL messages from the last height boundary (reference:
        consensus/replay.go:93-160)."""
        # Sanity: the WAL must NOT already contain an ENDHEIGHT for cs_height —
        # that would mean the stores are behind the WAL (the height fully
        # committed but state/block store not reflecting it), which WAL replay
        # cannot fix (reference: consensus/replay.go:115-125).
        done = self.wal.search_for_end_height(cs_height)
        if done is not None:
            raise RuntimeError(
                f"WAL should not contain #ENDHEIGHT {cs_height}; "
                "the state store is behind the WAL"
            )
        after = self.wal.search_for_end_height(cs_height - 1)
        if after is None:
            # no in-height messages for this height; nothing to replay
            return
        self.replay_mode = True
        try:
            for tm in after:
                msg = wal_blob_to_msg(tm.msg) if isinstance(tm.msg, WALMessageBlob) else None
                if msg is None:
                    continue
                if isinstance(msg, TimeoutInfo):
                    self._do_handle_timeout(msg)
                elif isinstance(msg, (ProposalMessage, BlockPartMessage, VoteMessage)):
                    with self._mtx:
                        self._handle_msg(MsgInfo(msg, tm.msg.peer_id))
        finally:
            self.replay_mode = False
