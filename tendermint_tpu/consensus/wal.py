"""Consensus write-ahead log (reference: consensus/wal.go:57,75,91,201,231,300).

Frame format mirrors the reference's WALEncoder: crc32c | length | protobuf
TimedWALMessage. Messages are replayed on restart to recover in-flight
consensus state; EndHeightMessage marks a completed height (fsync'd, the
crash-recovery anchor).

File rotation follows libs/autofile/group.go semantics (size-limited chunks
Head, Head.000, ...), simplified to a single directory of numbered chunks.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass

from tendermint_tpu.encoding import proto
from tendermint_tpu.utils import faults

MAX_MSG_SIZE_BYTES = 1024 * 1024  # reference: consensus/wal.go:32
DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024


class WALError(Exception):
    pass


class CorruptedWALError(WALError):
    pass


@dataclass
class TimedWALMessage:
    time_ns: int
    msg: object  # EndHeightMessage | MsgInfo-like | TimeoutInfo-like


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class WALMessageBlob:
    """Opaque consensus message payload: (kind, payload bytes, peer_id)."""

    kind: str
    payload: bytes
    peer_id: str = ""


def _encode_msg(m) -> bytes:
    w = proto.Writer()
    if isinstance(m, EndHeightMessage):
        w.message(1, proto.Writer().varint(1, m.height).out(), always=True)
    elif isinstance(m, WALMessageBlob):
        inner = (
            proto.Writer()
            .string(1, m.kind)
            .bytes(2, m.payload)
            .string(3, m.peer_id)
            .out()
        )
        w.message(2, inner, always=True)
    else:
        raise WALError(f"unknown WAL message type {type(m)}")
    return w.out()


def _decode_msg(buf: bytes):
    f = proto.fields(buf)
    if 1 in f:
        inner = proto.fields(f[1][-1])
        return EndHeightMessage(height=proto.as_sint64(inner.get(1, [0])[-1]))
    if 2 in f:
        inner = proto.fields(f[2][-1])
        return WALMessageBlob(
            kind=inner.get(1, [b""])[-1].decode(),
            payload=inner.get(2, [b""])[-1],
            peer_id=inner.get(3, [b""])[-1].decode() if 3 in inner else "",
        )
    raise CorruptedWALError("empty WAL message")


def _valid_frames(data: bytes):
    """Yield (pos, end, time_ns, msg) for each valid frame of a chunk,
    stopping at the first torn/truncated/corrupt/undecodable frame — the
    ONE definition of frame validity, shared by replay and repair so the
    two can never disagree on where the valid prefix ends."""
    pos = 0
    while pos + 8 <= len(data):
        crc, length = struct.unpack_from(">II", data, pos)
        if length > MAX_MSG_SIZE_BYTES or pos + 8 + length > len(data):
            return
        body = data[pos + 8 : pos + 8 + length]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return
        try:
            f2 = proto.fields(body)
            time_ns = proto.as_sint64(f2.get(1, [0])[-1])
            msg = _decode_msg(f2.get(2, [b""])[-1])
        except (CorruptedWALError, ValueError):
            return
        end = pos + 8 + length
        yield pos, end, time_ns, msg
        pos = end


class WAL:
    """reference: consensus/wal.go BaseWAL."""

    def __init__(self, path: str, head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT):
        self.dir = path
        self.head_size_limit = head_size_limit
        os.makedirs(self.dir, exist_ok=True)
        self._mtx = threading.Lock()
        self._head: object | None = None
        self._head_index = self._max_index()
        self._repair()
        self._open_head()

    # --- chunk management (autofile group light) ---------------------------

    def _chunk_path(self, index: int) -> str:
        return os.path.join(self.dir, f"wal.{index:06d}")

    def _indexes(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("wal."):
                try:
                    out.append(int(name[4:]))
                except ValueError:
                    pass
        return sorted(out)

    def _max_index(self) -> int:
        idx = self._indexes()
        return idx[-1] if idx else 0

    def _open_head(self) -> None:
        self._head = open(self._chunk_path(self._head_index), "ab")

    def _repair(self) -> None:
        """Make the on-disk log append-safe again after damage: replay
        stops at the first torn/corrupt frame in ANY chunk, so everything
        from that point on — the damaged chunk's tail, all later chunks,
        and any frame a reopened node would append — is unreachable. On
        open, find the first chunk with a non-clean tail, truncate it to
        its valid prefix, retire every later chunk (messages after a lost
        frame must not replay — ordering across the gap is broken), and
        point appends at the repaired chunk. Damaged originals are kept
        aside as .corrupted.N for forensics (reference:
        consensus/replay.go:73 repairWalFile).

        Crash-safe order: later chunks are retired highest-index-first,
        then the torn chunk is replaced via write-temp + fsync + hard-link
        original aside + atomic rename + directory fsync. At every
        intermediate state the replayable prefix is unchanged (replay
        still stops at the tear), and a re-crash just repeats the repair."""
        torn = None
        for index in self._indexes():
            path = self._chunk_path(index)
            with open(path, "rb") as f:
                data = f.read()
            end = 0
            for _pos, frame_end, _t, _m in _valid_frames(data):
                end = frame_end
            if end < len(data):
                torn = (index, data, end)
                break
        if torn is None:
            return
        index, data, end = torn
        for later in reversed([i for i in self._indexes() if i > index]):
            self._retire(self._chunk_path(later), keep_prefix=None)
        self._retire(self._chunk_path(index), keep_prefix=data[:end])
        self._head_index = index

    def _retire(self, path: str, keep_prefix: bytes | None) -> None:
        """Move `path` aside as .corrupted.N; when keep_prefix is given,
        atomically replace it with that prefix instead of removing it."""
        n = 0
        while os.path.exists(f"{path}.corrupted.{n}"):
            n += 1
        if keep_prefix is None:
            os.replace(path, f"{path}.corrupted.{n}")
        else:
            tmp = path + ".repair.tmp"
            with open(tmp, "wb") as dst:
                dst.write(keep_prefix)
                dst.flush()
                os.fsync(dst.fileno())
            os.link(path, f"{path}.corrupted.{n}")
            os.replace(tmp, path)
        dirfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    def _maybe_rotate(self) -> None:
        if self._head.tell() >= self.head_size_limit:
            self._head.close()
            self._head_index += 1
            self._open_head()

    # --- writes ------------------------------------------------------------

    def write(self, msg, time_ns: int = 0) -> None:
        """Buffered write (fsync only on write_sync; reference:
        consensus/wal.go:166-199)."""
        with self._mtx:
            self._write_locked(msg, time_ns)

    def write_sync(self, msg, time_ns: int = 0) -> None:
        with self._mtx:
            self._write_locked(msg, time_ns)
            faults.fire("wal.fsync")  # crash here loses the buffered frames
            self._head.flush()
            os.fsync(self._head.fileno())

    def _write_locked(self, msg, time_ns: int) -> None:
        body = proto.Writer().varint(1, time_ns).message(2, _encode_msg(msg), always=True).out()
        if len(body) > MAX_MSG_SIZE_BYTES:
            raise WALError(f"msg is too big: {len(body)} bytes, max: {MAX_MSG_SIZE_BYTES} bytes")
        crc = zlib.crc32(body) & 0xFFFFFFFF
        frame = struct.pack(">II", crc, len(body)) + body
        # torn/partial rules write a cut prefix of this frame and crash,
        # leaving on disk exactly what a power cut mid-append leaves.
        faults.torn_write("wal.write", self._head, frame)
        self._head.write(frame)
        self._maybe_rotate()

    def flush_and_sync(self) -> None:
        with self._mtx:
            faults.fire("wal.fsync")
            self._head.flush()
            os.fsync(self._head.fileno())

    def close(self) -> None:
        with self._mtx:
            if self._head is not None:
                self._head.flush()
                self._head.close()
                self._head = None

    # --- reads -------------------------------------------------------------

    def iter_messages(self, start_index: int | None = None):
        """Yield (TimedWALMessage, (chunk_index, offset)) across chunks,
        stopping at the first corrupt/truncated frame (crash tail)."""
        for index in self._indexes():
            if start_index is not None and index < start_index:
                continue
            path = self._chunk_path(index)
            with open(path, "rb") as f:
                data = f.read()
            end = 0
            for pos, fend, time_ns, msg in _valid_frames(data):
                yield TimedWALMessage(time_ns=time_ns, msg=msg), (index, pos)
                end = fend
            if end < len(data):
                return  # corrupt/torn tail: nothing after it is trustworthy

    def search_for_end_height(self, height: int):
        """Find messages after EndHeightMessage{height} (reference:
        consensus/wal.go:231-290). Returns list of messages after it, or
        None if not found."""
        found = False
        after: list[TimedWALMessage] = []
        for tm, _loc in self.iter_messages():
            if found:
                after.append(tm)
            elif isinstance(tm.msg, EndHeightMessage) and tm.msg.height == height:
                found = True
        return after if found else None
