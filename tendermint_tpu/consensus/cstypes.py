"""Consensus-internal types: round steps, RoundState, HeightVoteSet
(reference: consensus/types/round_state.go, consensus/types/height_vote_set.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote, is_vote_type_valid
from tendermint_tpu.types.vote_set import VoteSet

# RoundStepType (reference: consensus/types/round_state.go:13-40)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "RoundStepNewHeight",
    STEP_NEW_ROUND: "RoundStepNewRound",
    STEP_PROPOSE: "RoundStepPropose",
    STEP_PREVOTE: "RoundStepPrevote",
    STEP_PREVOTE_WAIT: "RoundStepPrevoteWait",
    STEP_PRECOMMIT: "RoundStepPrecommit",
    STEP_PRECOMMIT_WAIT: "RoundStepPrecommitWait",
    STEP_COMMIT: "RoundStepCommit",
}


@dataclass
class RoundState:
    """reference: consensus/types/round_state.go:65-120."""

    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: Time = field(default_factory=Time.zero)
    commit_time: Time = field(default_factory=Time.zero)
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: "HeightVoteSet | None" = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def step_name(self) -> str:
        return STEP_NAMES.get(self.step, f"Unknown({self.step})")


class HeightVoteSetError(Exception):
    pass


class ErrGotVoteFromUnwantedRound(HeightVoteSetError):
    def __init__(self):
        super().__init__("peer has sent a vote that does not match our round for more than one round")


class HeightVoteSet:
    """Prevotes + precommits for every round of one height, with bounded
    peer catch-up rounds (reference: consensus/types/height_vote_set.go:34-200)."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self._mtx = threading.RLock()
        self.reset(height, val_set)

    def reset(self, height: int, val_set: ValidatorSet) -> None:
        with self._mtx:
            self.height = height
            self.val_set = val_set
            self.round = 0
            self.round_vote_sets: dict[int, tuple[VoteSet, VoteSet]] = {}
            self.peer_catchup_rounds: dict[str, list[int]] = {}
            self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self.round_vote_sets:
            raise HeightVoteSetError("addRound() for an existing round")
        prevotes = VoteSet(self.chain_id, self.height, round_, PREVOTE_TYPE, self.val_set)
        precommits = VoteSet(self.chain_id, self.height, round_, PRECOMMIT_TYPE, self.val_set)
        self.round_vote_sets[round_] = (prevotes, precommits)

    def set_round(self, round_: int) -> None:
        """Creates vote sets up to round_ (reference: height_vote_set.go:86-100)."""
        with self._mtx:
            new_round = self.round - 1
            if self.round != 0 and round_ < new_round:
                raise HeightVoteSetError("SetRound() must increment hvs.round")
            for r in range(max(new_round, 0), round_ + 1):
                if r not in self.round_vote_sets:
                    self._add_round(r)
            self.round = round_

    def add_vote(self, vote: Vote, peer_id: str, verified: bool = False) -> bool:
        """reference: height_vote_set.go:117-150."""
        with self._mtx:
            if not is_vote_type_valid(vote.type):
                return False
            vote_set = self._get_vote_set(vote.round, vote.type)
            if vote_set is None:
                rndz = self.peer_catchup_rounds.get(peer_id, [])
                if len(rndz) < 2:
                    self._add_round(vote.round)
                    vote_set = self._get_vote_set(vote.round, vote.type)
                    rndz.append(vote.round)
                    self.peer_catchup_rounds[peer_id] = rndz
                else:
                    raise ErrGotVoteFromUnwantedRound()
            return vote_set.add_vote(vote, verified=verified)

    def prevotes(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, PREVOTE_TYPE)

    def precommits(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, PRECOMMIT_TYPE)

    def pol_info(self) -> tuple[int, BlockID]:
        """Last round with a prevote maj23 (reference: height_vote_set.go:153-164)."""
        with self._mtx:
            for r in range(self.round, -1, -1):
                rvs = self._get_vote_set(r, PREVOTE_TYPE)
                if rvs is not None:
                    bid, ok = rvs.two_thirds_majority()
                    if ok:
                        return r, bid
            return -1, BlockID()

    def _get_vote_set(self, round_: int, vote_type: int) -> VoteSet | None:
        rvs = self.round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs[0] if vote_type == PREVOTE_TYPE else rvs[1]

    def set_peer_maj23(self, round_: int, vote_type: int, peer_id: str,
                       block_id: BlockID) -> None:
        """reference: height_vote_set.go:185-200."""
        with self._mtx:
            if not is_vote_type_valid(vote_type):
                raise HeightVoteSetError(f"SetPeerMaj23: invalid vote type {vote_type}")
            vote_set = self._get_vote_set(round_, vote_type)
            if vote_set is None:
                return
            vote_set.set_peer_maj23(peer_id, block_id)
