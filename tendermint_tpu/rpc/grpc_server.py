"""gRPC BroadcastAPI (reference: rpc/grpc/types.proto + api.go).

Service tendermint.rpc.grpc.BroadcastAPI:
  Ping(RequestPing{}) -> ResponsePing{}
  BroadcastTx(RequestBroadcastTx{tx=1}) -> ResponseBroadcastTx{
      check_tx=1 abci.ResponseCheckTx, deliver_tx=2 abci.ResponseDeliverTx}

No generated stubs: the service registers a generic handler with raw-bytes
(de)serializers and the messages go through the framework's own proto codec,
so the wire format matches a protoc-generated Go client exactly
(BroadcastTx commits like the reference's core.BroadcastTxCommit).
"""

from __future__ import annotations

from concurrent import futures

import grpc

from tendermint_tpu.encoding import proto

SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


def _encode_check_tx(r) -> bytes:
    return (proto.Writer().uvarint(1, r.code).bytes(2, r.data)
            .string(3, r.log).varint(5, r.gas_wanted).varint(6, r.gas_used)
            .out())


class BroadcastAPIServer:
    """reference: rpc/grpc/api.go broadcastAPI."""

    def __init__(self, node, laddr: str, max_workers: int = 8):
        self._node = node
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        host_port = laddr.split("://", 1)[-1]
        port = self._server.add_insecure_port(host_port)
        host = host_port.rsplit(":", 1)[0]
        self.laddr = f"{host}:{port}"

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    # --- handlers -----------------------------------------------------------

    def _ping(self, request: bytes, context) -> bytes:
        return b""  # ResponsePing{}

    def _broadcast_tx(self, request: bytes, context) -> bytes:
        f = proto.fields(request)
        tx = f.get(1, [b""])[-1]
        from tendermint_tpu.rpc import core as rpc_core

        env = rpc_core.Environment(self._node)
        try:
            res = rpc_core.broadcast_tx_commit(env, tx)
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
            return b""
        w = proto.Writer()
        check = (proto.Writer()
                 .uvarint(1, int(res["check_tx"].get("code", 0)))
                 .string(3, res["check_tx"].get("log", "") or "").out())
        deliver = (proto.Writer()
                   .uvarint(1, int(res["deliver_tx"].get("code", 0)))
                   .string(3, res["deliver_tx"].get("log", "") or "").out())
        w.message(1, check, always=True)
        w.message(2, deliver, always=True)
        return w.out()

    def _handler(self):
        rpcs = {
            "Ping": self._ping,
            "BroadcastTx": self._broadcast_tx,
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                # path: /tendermint.rpc.grpc.BroadcastAPI/<Method>
                parts = handler_call_details.method.lstrip("/").split("/")
                if len(parts) != 2 or parts[0] != SERVICE:
                    return None
                fn = rpcs.get(parts[1])
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    fn,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        return Handler()


class BroadcastAPIClient:
    """Minimal client for the BroadcastAPI (tests / tooling)."""

    def __init__(self, addr: str):
        self._channel = grpc.insecure_channel(addr)
        self._ping = self._channel.unary_unary(
            f"/{SERVICE}/Ping",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        self._btx = self._channel.unary_unary(
            f"/{SERVICE}/BroadcastTx",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

    def ping(self) -> bool:
        self._ping(b"", timeout=5)
        return True

    def broadcast_tx(self, tx: bytes, timeout: float = 30.0) -> dict:
        raw = self._btx(proto.Writer().bytes(1, tx).out(), timeout=timeout)
        f = proto.fields(raw)
        out = {}
        for key, num in (("check_tx", 1), ("deliver_tx", 2)):
            m = proto.fields(f.get(num, [b""])[-1])
            out[key] = {
                "code": m.get(1, [0])[-1],
                "log": m.get(3, [b""])[-1].decode() if 3 in m else "",
            }
        return out

    def close(self) -> None:
        self._channel.close()
